"""ImageRecordIter: the production image pipeline (ref:
src/io/iter_image_recordio_2.cc ImageRecordIter2:660 — N decode
threads + augment + BatchLoader + double-buffered PrefetcherIter,
src/io/iter_prefetcher.h:47).

Same architecture, host-side: a thread pool decodes+augments records
in parallel (PIL releases the GIL around codec work), a batcher
assembles NCHW arrays, and a one-slot-deep background prefetcher
overlaps the next batch's decode with the current device step —
the dmlc ThreadedIter double-buffer."""
import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

import warnings

from .. import recordio as rio
from ..io.io import (DataBatch, DataDesc, DataIter, _bounded_get,
                     _stop_aware_put)
from ..io.sharding import shard_keys
from ..ndarray.ndarray import array as nd_array
from ..resilience import DataPipelineError, inject
from ..utils.env import get_env
from .image import CreateAugmenter, augment_to_chw, imdecode

__all__ = ["ImageRecordIter"]


class ImageRecordIter(DataIter):
    """Reads .rec (+ optional .idx) shards (ref:
    iter_image_recordio_2.cc; python surface matches the reference's
    generated ImageRecordIter)."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_width=1, shuffle=False, rand_crop=False,
                 rand_mirror=False, mean_r=0, mean_g=0, mean_b=0,
                 std_r=0, std_g=0, std_b=0, resize=0,
                 preprocess_threads=4, prefetch_buffer=2,
                 data_name="data", label_name="softmax_label",
                 round_batch=True, num_parts=1, part_index=0,
                 aug_list=None, **kwargs):
        super().__init__(batch_size)
        if kwargs:
            warnings.warn(
                f"ImageRecordIter: ignoring unsupported options "
                f"{sorted(kwargs)}")
        self.round_batch = round_batch
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        mean = [mean_r, mean_g, mean_b] if (mean_r or mean_g or
                                            mean_b) else None
        std = [std_r, std_g, std_b] if (std_r or std_g or std_b) \
            else None
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(self.data_shape, resize=resize,
                            rand_crop=rand_crop,
                            rand_mirror=rand_mirror, mean=mean,
                            std=std)
        # native fast path (src/imgdec): decode+crop+mirror+normalize
        # in one C call with a persistent thread pool — the default
        # augmenter chain minus random crop.  PIL decode is GIL-bound
        # (~1k img/s flat regardless of threads); this is the
        # reference's decode-threads answer (iter_image_recordio_2).
        # Gated to the exactly-equivalent config: no custom augs, no
        # random crop, resize==0 (the native shorter-edge kernel is
        # not pixel-identical to PIL's antialiased resize), JPEG
        # records (checked per batch by magic bytes; non-JPEG batches
        # fall back to PIL transparently).
        self._native = None
        if (aug_list is None and not rand_crop and resize == 0
                and self.data_shape[0] == 3
                and os.environ.get("MXTPU_NATIVE_DECODE", "1") != "0"):
            from . import native_dec
            if native_dec.available():
                self._native = dict(
                    mirror_p=0.5 if rand_mirror else 0.0,
                    mean=np.asarray(mean, np.float32)
                    if mean is not None else None,
                    # CreateAugmenter only normalizes when mean is
                    # set; std alone must match that (no-op)
                    std=np.asarray(std, np.float32)
                    if std is not None and mean is not None else None,
                    nthreads=int(preprocess_threads))
        self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        # load the record offsets once; shuffle epoch-wise
        idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
        if os.path.exists(idx_path):
            self._rec = rio.MXIndexedRecordIO(idx_path, path_imgrec,
                                              "r")
            # contiguous record-boundary partition (exactly-once
            # coverage across parts; io/sharding.py — the floor
            # arithmetic keeps part edges exact for every N/P)
            self._keys = shard_keys(list(self._rec.keys), num_parts,
                                    part_index)
        else:
            self._rec = rio.MXRecordIO(path_imgrec, "r")
            self._keys = None
            assert num_parts == 1, \
                "sharded reads need an .idx file"
        self._lock = threading.Lock()
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        lshape = (batch_size,) if label_width == 1 \
            else (batch_size, label_width)
        self.provide_label = [DataDesc(label_name, lshape)]
        self._prefetch_q = queue.Queue(maxsize=prefetch_buffer)
        self._producer = None
        self._stop = threading.Event()
        self._path = path_imgrec
        self._bad_records = 0       # cumulative corrupt-record count
        self._nbatch = 0            # batches delivered this epoch
        self._records_consumed = 0  # stream events through last
                                    # delivered batch (quarantines
                                    # consume extra records, so this
                                    # is NOT nbatch * batch_size)
        self._skip_batches = 0      # replay-discard count (skip())
        self._resume_pending = False
        self._resume_nbatch = 0
        self._resume_consumed = 0
        self._resume_skip = 0
        self.reset()

    # ------------------------------------------------------------ epoch
    def reset(self):
        self._drain()
        if self._resume_pending:
            # a just-restored position survives the train loop's
            # epoch-start reset (one-shot): keys order came from the
            # state_dict, so no reshuffle, and the producer restarts
            # the stream at the recorded consumption point
            self._resume_pending = False
            self._nbatch = self._resume_nbatch
            self._records_consumed = self._resume_consumed
            self._skip_batches = self._resume_skip
            self._resume_skip = 0
        else:
            if self._keys is not None and self.shuffle:
                np.random.shuffle(self._keys)
            self._nbatch = 0
            self._records_consumed = 0
            self._skip_batches = 0
        if self._keys is None:
            self._rec.reset()
        self._stop.clear()
        self._producer = threading.Thread(target=self._produce,
                                          daemon=True)
        self._producer.start()

    def state_dict(self):
        """Position snapshot: delivered-batch count + the exact
        stream-consumption count through the last delivered batch
        (quarantined records consume extra stream events, so this is
        not derivable from nbatch) + epoch key order + cumulative
        bad-record count + numpy RNG state (shuffle source).  The
        producer thread reads ahead of next(), so delivered-batch
        accounting — not the reader cursor — is the resume point."""
        if self._resume_pending:
            nbatch, consumed, skip = (self._resume_nbatch,
                                      self._resume_consumed,
                                      self._resume_skip)
        else:
            nbatch, consumed, skip = (self._nbatch,
                                      self._records_consumed,
                                      self._skip_batches)
        return {"type": "ImageRecordIter",
                "nbatch": nbatch,
                "consumed": consumed,
                "skip": skip,
                "keys": list(self._keys)
                if self._keys is not None else None,
                "bad_records": self._bad_records,
                "np_rng": np.random.get_state()}

    def load_state_dict(self, state):
        if state.get("type") != "ImageRecordIter":
            raise ValueError(
                f"state_dict type {state.get('type')!r} does not "
                "match ImageRecordIter")
        keys = state.get("keys")
        if (keys is None) != (self._keys is None):
            raise ValueError(
                "iterator state and this ImageRecordIter disagree "
                "about having an .idx file — state from a different "
                "dataset?")
        self._drain()
        if keys is not None:
            self._keys = list(keys)
        self._bad_records = int(state.get("bad_records", 0))
        if state.get("np_rng") is not None:
            np.random.set_state(state["np_rng"])
        self._resume_nbatch = int(state["nbatch"])
        self._resume_consumed = int(state["consumed"])
        self._resume_skip = int(state.get("skip", 0))
        self._resume_pending = True

    def skip(self, num_batches):
        """Fast-forward ``num_batches``: the producer replays them as
        discards from the recorded consumption point — assembling
        (and decoding) but not delivering — which stays exact even
        when quarantined records shifted per-batch consumption."""
        if self._resume_pending:
            base, consumed, skip = (self._resume_nbatch,
                                    self._resume_consumed,
                                    self._resume_skip)
        else:
            base, consumed, skip = (self._nbatch,
                                    self._records_consumed,
                                    self._skip_batches)
        self._resume_nbatch = base + num_batches
        self._resume_consumed = consumed
        self._resume_skip = skip + num_batches
        self._resume_pending = True
        self.reset()

    def _drain(self):
        """Stop the producer and empty the queue race-free: the
        producer's stop-aware put() exits on _stop, we JOIN it, and
        only then drain — so no stale item can land after the drain
        (the mid-epoch-reset hazard of a naive drain-then-join)."""
        if self._producer is not None:
            self._stop.set()
            while self._producer.is_alive():
                try:  # unblock a producer waiting in put()
                    self._prefetch_q.get_nowait()
                except queue.Empty:
                    pass
                self._producer.join(timeout=0.05)
            self._producer = None
        try:
            while True:
                self._prefetch_q.get_nowait()
        except queue.Empty:
            pass

    # ------------------------------------------------------------ workers
    def _read_raw(self, i):
        with self._lock:
            if self._keys is not None:
                return self._rec.read_idx(self._keys[i])
            return self._rec.read()

    def _decode_unpacked(self, pair):
        header, img_bytes = pair
        arr = augment_to_chw(imdecode(img_bytes), self.auglist)
        label = np.atleast_1d(np.asarray(header.label, np.float32))
        return arr, label

    def _safe_decode(self, pair):
        """(arr, label) on success, (None, exc) on a decode failure —
        run in the pool, where a raise would be per-future noise; the
        producer turns failures into quarantine decisions."""
        try:
            return self._decode_unpacked(pair)
        except Exception as exc:
            return None, exc

    def _quarantine(self, exc, where):
        """Count one corrupt record against MXTPU_MAX_BAD_RECORDS:
        skip-and-log within the budget, raise past it."""
        self._bad_records += 1
        from .. import telemetry
        telemetry.counter("data_quarantined_records_total").inc()
        budget = get_env("MXTPU_MAX_BAD_RECORDS")
        if self._bad_records > budget:
            raise DataPipelineError(
                f"ImageRecordIter: {self._bad_records} corrupt "
                f"record(s) in {self._path} exceed "
                f"MXTPU_MAX_BAD_RECORDS={budget} (last failure at "
                f"{where}: {exc}); raise the budget to tolerate "
                "more, or repair the dataset") from exc
        warnings.warn(
            f"ImageRecordIter: skipping corrupt record in "
            f"{self._path} ({where}: {exc}); bad-record budget "
            f"{self._bad_records}/{budget}", RuntimeWarning)

    def _put(self, item):
        """Stop-aware put so a blocked producer can exit on reset."""
        return _stop_aware_put(self._prefetch_q, self._stop, item)

    def _records(self, consumed):
        """Generator of unpacked (header, img_bytes) pairs starting
        at stream event ``consumed["n"]``, quarantining corrupt
        reads/unpacks: the sequential backend resyncs the stream to
        the next magic, the keyed backend skips the bad key.

        ``consumed["n"]`` counts *stream events* — yielded records,
        unpack failures, and bad reads (one event per skipped key /
        resynced region) — so it is the exact resume coordinate even
        when quarantine consumed extra records per batch (keyed path:
        it equals the key index)."""
        n = len(self._keys) if self._keys is not None else None
        while True:
            i = consumed["n"]
            if n is not None and i >= n:
                return
            try:
                raw = self._read_raw(i)
            except IOError as exc:
                consumed["n"] += 1
                self._quarantine(exc, "read")
                if n is None:
                    with self._lock:
                        if self._rec.resync() is None:
                            return      # no further record magic
                continue
            if raw is None:
                return
            consumed["n"] += 1
            try:
                pair = rio.unpack(raw)
            except Exception as exc:
                self._quarantine(exc, "unpack")
                continue
            yield pair

    def _spool_sequential(self, num_events):
        """Sequential (no-.idx) resume: spool past ``num_events``
        already-consumed stream events without decoding, using the
        same event accounting as :meth:`_records` (a bad read +
        resync is one event) and without re-counting quarantines the
        pre-checkpoint run already charged to the budget."""
        left = num_events
        while left > 0 and not self._stop.is_set():
            try:
                if self._rec.read() is None:
                    return
            except IOError:
                with self._lock:
                    if self._rec.resync() is None:
                        return
            left -= 1

    def _produce(self):
        try:
            n = len(self._keys) if self._keys is not None else None
            consumed = {"n": self._records_consumed}
            skip = self._skip_batches
            if n is None and consumed["n"]:
                self._spool_sequential(consumed["n"])
            rec_gen = self._records(consumed)
            while not self._stop.is_set():
                inject("data", "record_batch")
                pairs = []
                while len(pairs) < self.batch_size:
                    pair = next(rec_gen, None)
                    if pair is None:
                        break
                    pairs.append(pair)
                if not pairs:
                    break
                pad = self.batch_size - len(pairs)
                c, h, w = self.data_shape
                data = np.zeros((self.batch_size, c, h, w),
                                np.float32)
                label = np.zeros((self.batch_size, self.label_width),
                                 np.float32)
                filled = 0
                done = False
                # libjpeg-only: non-JPEG batches (PNG/BMP) or jpegs
                # libjpeg rejects but PIL handles (CMYK) fall back to
                # the PIL path on the SAME unpacked records — never
                # abort what PIL could decode
                if self._native is not None and \
                        all(ib[:2] == b"\xff\xd8" for _, ib in pairs):
                    from . import native_dec
                    cfg = self._native
                    imgs = [ib for _, ib in pairs]
                    mirror = None
                    if cfg["mirror_p"] > 0:
                        mirror = (np.random.rand(len(imgs))
                                  < cfg["mirror_p"])
                    try:
                        native_dec.decode_batch(
                            imgs, (h, w), mirror=mirror,
                            mean=cfg["mean"], std=cfg["std"],
                            nthreads=cfg["nthreads"],
                            out=data[:len(imgs)])
                        done = True
                    except ValueError:
                        pass    # PIL fallback below decides
                if done:
                    for j, (header, _) in enumerate(pairs):
                        lab = np.atleast_1d(np.asarray(
                            header.label, np.float32))
                        label[j] = lab[:self.label_width]
                    filled = len(pairs)
                else:
                    # PIL path with per-record quarantine: decode
                    # failures are skipped and replaced from the
                    # stream so mid-epoch batches stay full
                    pending = pairs
                    while pending:
                        decoded = list(self._pool.map(
                            self._safe_decode, pending))
                        lost = 0
                        for arr, payload in decoded:
                            if arr is None:
                                self._quarantine(payload, "decode")
                                lost += 1
                            elif filled < self.batch_size:
                                data[filled] = arr
                                label[filled] = \
                                    payload[:self.label_width]
                                filled += 1
                        if not lost:
                            break
                        pending = []
                        while len(pending) < lost:
                            pair = next(rec_gen, None)
                            if pair is None:
                                break
                            pending.append(pair)
                    pad = self.batch_size - filled
                if pad > 0 and self.round_batch and n is not None:
                    # wrap the tail with epoch-start samples (ref:
                    # round_batch semantics of the C++ iterator);
                    # wrap filler is stripped by pad-aware consumers,
                    # so a corrupt wrap record is simply skipped
                    j = 0
                    while filled < self.batch_size and j < 2 * n:
                        try:
                            arr, lab = self._decode_unpacked(
                                rio.unpack(self._read_raw(j % n)))
                        except Exception:
                            j += 1
                            continue
                        data[filled] = arr
                        label[filled] = lab[:self.label_width]
                        filled += 1
                        j += 1
                if skip > 0:
                    # replay-discard (skip()): the batch was
                    # assembled so consumption advanced exactly as in
                    # the original run, but it was already delivered
                    # pre-checkpoint — drop it
                    skip -= 1
                    if pad > 0:
                        break
                    continue
                if not self._put((data, label, pad, consumed["n"])):
                    return  # reset() interrupted us; no sentinel
                if pad > 0:
                    break
            self._put(None)  # epoch sentinel
        except Exception as e:  # surface errors in the consumer
            self._put(("error", e))

    # ------------------------------------------------------------ iter
    def next(self):
        if self._resume_pending:
            self.reset()    # applies the restored position
        if self._producer is None:
            raise StopIteration  # epoch ended; call reset()
        item = _bounded_get(self._prefetch_q,
                            f"ImageRecordIter({self._path})",
                            thread=self._producer)
        if item is None:
            self._producer.join(timeout=5)
            self._producer = None
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] == "error":
            self._producer = None
            exc = item[1]
            if isinstance(exc, DataPipelineError):
                raise exc
            err = DataPipelineError(
                f"ImageRecordIter({self._path}) producer raised "
                f"{type(exc).__name__}: {exc}")
            err.__cause__ = exc
            raise err
        data, label, pad, consumed = item
        self._nbatch += 1
        from .. import telemetry
        telemetry.counter("prefetch_batches_total").inc()
        self._records_consumed = consumed
        self._skip_batches = 0   # any replay-discard phase is over
        label_out = label[:, 0] if self.label_width == 1 else label
        return DataBatch([nd_array(data)], [nd_array(label_out)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __del__(self):
        try:
            self._drain()
            self._pool.shutdown(wait=False)
        except Exception:
            pass
