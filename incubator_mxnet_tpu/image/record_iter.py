"""ImageRecordIter: the production image pipeline (ref:
src/io/iter_image_recordio_2.cc ImageRecordIter2:660 — N decode
threads + augment + BatchLoader + double-buffered PrefetcherIter,
src/io/iter_prefetcher.h:47).

Same architecture, host-side: a thread pool decodes+augments records
in parallel (PIL releases the GIL around codec work), a batcher
assembles NCHW arrays, and a one-slot-deep background prefetcher
overlaps the next batch's decode with the current device step —
the dmlc ThreadedIter double-buffer."""
import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

import warnings

from .. import recordio as rio
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray.ndarray import array as nd_array
from .image import CreateAugmenter, augment_to_chw, imdecode

__all__ = ["ImageRecordIter"]


class ImageRecordIter(DataIter):
    """Reads .rec (+ optional .idx) shards (ref:
    iter_image_recordio_2.cc; python surface matches the reference's
    generated ImageRecordIter)."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_width=1, shuffle=False, rand_crop=False,
                 rand_mirror=False, mean_r=0, mean_g=0, mean_b=0,
                 std_r=0, std_g=0, std_b=0, resize=0,
                 preprocess_threads=4, prefetch_buffer=2,
                 data_name="data", label_name="softmax_label",
                 round_batch=True, num_parts=1, part_index=0,
                 aug_list=None, **kwargs):
        super().__init__(batch_size)
        if kwargs:
            warnings.warn(
                f"ImageRecordIter: ignoring unsupported options "
                f"{sorted(kwargs)}")
        self.round_batch = round_batch
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        mean = [mean_r, mean_g, mean_b] if (mean_r or mean_g or
                                            mean_b) else None
        std = [std_r, std_g, std_b] if (std_r or std_g or std_b) \
            else None
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(self.data_shape, resize=resize,
                            rand_crop=rand_crop,
                            rand_mirror=rand_mirror, mean=mean,
                            std=std)
        # native fast path (src/imgdec): decode+crop+mirror+normalize
        # in one C call with a persistent thread pool — the default
        # augmenter chain minus random crop.  PIL decode is GIL-bound
        # (~1k img/s flat regardless of threads); this is the
        # reference's decode-threads answer (iter_image_recordio_2).
        # Gated to the exactly-equivalent config: no custom augs, no
        # random crop, resize==0 (the native shorter-edge kernel is
        # not pixel-identical to PIL's antialiased resize), JPEG
        # records (checked per batch by magic bytes; non-JPEG batches
        # fall back to PIL transparently).
        self._native = None
        if (aug_list is None and not rand_crop and resize == 0
                and self.data_shape[0] == 3
                and os.environ.get("MXTPU_NATIVE_DECODE", "1") != "0"):
            from . import native_dec
            if native_dec.available():
                self._native = dict(
                    mirror_p=0.5 if rand_mirror else 0.0,
                    mean=np.asarray(mean, np.float32)
                    if mean is not None else None,
                    # CreateAugmenter only normalizes when mean is
                    # set; std alone must match that (no-op)
                    std=np.asarray(std, np.float32)
                    if std is not None and mean is not None else None,
                    nthreads=int(preprocess_threads))
        self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        # load the record offsets once; shuffle epoch-wise
        idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
        if os.path.exists(idx_path):
            self._rec = rio.MXIndexedRecordIO(idx_path, path_imgrec,
                                              "r")
            keys = list(self._rec.keys)[part_index::num_parts]
            self._keys = keys
        else:
            self._rec = rio.MXRecordIO(path_imgrec, "r")
            self._keys = None
            assert num_parts == 1, \
                "sharded reads need an .idx file"
        self._lock = threading.Lock()
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        lshape = (batch_size,) if label_width == 1 \
            else (batch_size, label_width)
        self.provide_label = [DataDesc(label_name, lshape)]
        self._prefetch_q = queue.Queue(maxsize=prefetch_buffer)
        self._producer = None
        self._stop = threading.Event()
        self.reset()

    # ------------------------------------------------------------ epoch
    def reset(self):
        self._drain()
        if self._keys is not None and self.shuffle:
            np.random.shuffle(self._keys)
        if self._keys is None:
            self._rec.reset()
        self._cursor = 0
        self._stop.clear()
        self._producer = threading.Thread(target=self._produce,
                                          daemon=True)
        self._producer.start()

    def _drain(self):
        """Stop the producer and empty the queue race-free: the
        producer's stop-aware put() exits on _stop, we JOIN it, and
        only then drain — so no stale item can land after the drain
        (the mid-epoch-reset hazard of a naive drain-then-join)."""
        if self._producer is not None:
            self._stop.set()
            while self._producer.is_alive():
                try:  # unblock a producer waiting in put()
                    self._prefetch_q.get_nowait()
                except queue.Empty:
                    pass
                self._producer.join(timeout=0.05)
            self._producer = None
        try:
            while True:
                self._prefetch_q.get_nowait()
        except queue.Empty:
            pass

    # ------------------------------------------------------------ workers
    def _read_raw(self, i):
        with self._lock:
            if self._keys is not None:
                return self._rec.read_idx(self._keys[i])
            return self._rec.read()

    def _decode_one(self, raw):
        return self._decode_unpacked(rio.unpack(raw))

    def _decode_unpacked(self, pair):
        header, img_bytes = pair
        arr = augment_to_chw(imdecode(img_bytes), self.auglist)
        label = np.atleast_1d(np.asarray(header.label, np.float32))
        return arr, label

    def _put(self, item):
        """Stop-aware put so a blocked producer can exit on reset."""
        while not self._stop.is_set():
            try:
                self._prefetch_q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self):
        try:
            n = len(self._keys) if self._keys is not None else None
            i = 0
            while not self._stop.is_set():
                raws = []
                while len(raws) < self.batch_size:
                    if n is not None and i >= n:
                        break
                    raw = self._read_raw(i)
                    if raw is None:
                        break
                    raws.append(raw)
                    i += 1
                if not raws:
                    break
                pad = self.batch_size - len(raws)
                if pad > 0 and self.round_batch and n is not None:
                    # wrap the tail with epoch-start samples (ref:
                    # round_batch semantics of the C++ iterator)
                    for j in range(pad):
                        raws.append(self._read_raw(j % n))
                c, h, w = self.data_shape
                data = np.zeros((self.batch_size, c, h, w),
                                np.float32)
                label = np.zeros((self.batch_size, self.label_width),
                                 np.float32)
                done = False
                if self._native is not None:
                    unpacked = [rio.unpack(raw) for raw in raws]
                    # libjpeg-only: non-JPEG batches (PNG/BMP) or
                    # jpegs libjpeg rejects but PIL handles (CMYK)
                    # fall back to the PIL path on the SAME unpacked
                    # records — never abort what PIL could decode
                    if all(ib[:2] == b"\xff\xd8"
                           for _, ib in unpacked):
                        from . import native_dec
                        cfg = self._native
                        imgs = [ib for _, ib in unpacked]
                        mirror = None
                        if cfg["mirror_p"] > 0:
                            mirror = (np.random.rand(len(imgs))
                                      < cfg["mirror_p"])
                        try:
                            native_dec.decode_batch(
                                imgs, (h, w), mirror=mirror,
                                mean=cfg["mean"], std=cfg["std"],
                                nthreads=cfg["nthreads"],
                                out=data[:len(imgs)])
                            done = True
                        except ValueError:
                            pass    # PIL fallback below decides
                    if done:
                        for j, (header, _) in enumerate(unpacked):
                            lab = np.atleast_1d(np.asarray(
                                header.label, np.float32))
                            label[j] = lab[:self.label_width]
                    else:
                        decoded = list(self._pool.map(
                            self._decode_unpacked, unpacked))
                        for j, (arr, lab) in enumerate(decoded):
                            data[j] = arr
                            label[j] = lab[:self.label_width]
                        done = True
                if not done:
                    decoded = list(self._pool.map(self._decode_one,
                                                  raws))
                    for j, (arr, lab) in enumerate(decoded):
                        data[j] = arr
                        label[j] = lab[:self.label_width]
                if not self._put((data, label, pad)):
                    return  # reset() interrupted us; no sentinel
                if pad > 0:
                    break
            self._put(None)  # epoch sentinel
        except Exception as e:  # surface errors in the consumer
            self._put(("error", e))

    # ------------------------------------------------------------ iter
    def next(self):
        if self._producer is None:
            raise StopIteration  # epoch ended; call reset()
        item = self._prefetch_q.get()
        if item is None:
            self._producer.join(timeout=5)
            self._producer = None
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] == "error":
            self._producer = None
            raise item[1]
        data, label, pad = item
        label_out = label[:, 0] if self.label_width == 1 else label
        return DataBatch([nd_array(data)], [nd_array(label_out)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __del__(self):
        try:
            self._drain()
            self._pool.shutdown(wait=False)
        except Exception:
            pass
