"""Unified run telemetry: metrics registry, step-timeline spans, and
periodic snapshot emission (docs/observability.md).

The reference framework's observability is per-op profiling
(src/engine/profiler.h -> profiler.py here) and the debug Monitor
(python/mxnet/monitor.py).  Production TPU runs additionally need
*always-on, low-overhead* run telemetry — the Prometheus-style metric
registry + trace-span timeline of modern training stacks — so an
operator can see where time and data are going on a hung or
slowly-diverging job without attaching a debugger.  Three layers:

- :class:`MetricRegistry` — process-wide Counter / Gauge / Histogram
  (bounded reservoir) store.  Thread-safe; every accessor degrades to
  a shared no-op when ``MXTPU_TELEMETRY=0``, so disabled runs pay one
  env read and nothing else (no locks, no allocation, no writes).
- :func:`span` — a context manager timing a wall-clock section into
  the registry (``span_<name>_seconds`` histogram) AND into the
  chrome://tracing profiler stream when the profiler is running, so
  coarse step phases and fine per-op events land on one timeline.
  Spans never touch device values: they cost two ``perf_counter``
  reads and add NO device->host syncs (the step sentinel's transfer
  budget — one scalar read per MXTPU_GUARD_INTERVAL — is preserved;
  proven by the transfer-budget test in tests/test_telemetry.py).
- :class:`TelemetryEmitter` — a daemon thread flushing periodic JSONL
  snapshots (``MXTPU_TELEMETRY_FILE``, every
  ``MXTPU_TELEMETRY_INTERVAL`` seconds, rotated at
  ``MXTPU_TELEMETRY_MAX_MB``) plus an atomically-replaced
  Prometheus-style textfile (``<file>.prom``) for node-exporter-style
  scrapers.

Per-worker snapshots additionally ride the resilience heartbeat files
(:func:`heartbeat_payload`, appended by ``resilience._beat`` as a
second line) so ``tools/launch.py`` can aggregate ranks into a
periodic cluster status line and a final run report without any extra
channel.

Stdlib-only and import-light (like resilience.py): dist workers can
import it before jax is up.  Metric *names* are governed: every
literal name passed to counter()/gauge()/histogram()/span() must be
declared in the catalog table of docs/observability.md — enforced by
``ci/lint.py``.
"""
import json
import os
import re
import threading
import time
from collections import deque

from .utils.env import get_env

__all__ = ["Counter", "Gauge", "Histogram", "MetricRegistry",
           "TelemetryEmitter", "AnomalyWatch", "enabled",
           "get_registry", "counter", "gauge", "histogram", "span",
           "snapshot", "prometheus_text", "heartbeat_payload",
           "start_emitter", "maybe_start_emitter", "stop_emitter",
           "anomaly_watch", "anomaly_verdicts"]


def enabled():
    """Whether telemetry is armed (``MXTPU_TELEMETRY``, default on).

    The disabled fast path is this one env read: every factory below
    returns the shared no-op metric/span, so instrumented code sites
    stay branch-free."""
    return get_env("MXTPU_TELEMETRY")


# ---------------------------------------------------------------------------
# metric primitives
# ---------------------------------------------------------------------------


class Counter:
    """Monotonically increasing count (events, retries, bad steps)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, loss scale)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = float(v)

    @property
    def value(self):
        return self._value


class Histogram:
    """Distribution with exact count/sum/min/max and a *bounded*
    reservoir of the most recent ``max_samples`` observations for
    percentiles — memory stays O(max_samples) over any run length."""

    __slots__ = ("name", "count", "sum", "min", "max", "_samples",
                 "_lock")

    def __init__(self, name, max_samples=512):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._samples = deque(maxlen=max_samples)
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            self._samples.append(v)

    def percentile(self, q):
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return None
        idx = min(len(data) - 1, max(0, int(q * (len(data) - 1))))
        return data[idx]

    def stats(self):
        with self._lock:
            data = sorted(self._samples)
            out = {"count": self.count, "sum": self.sum,
                   "min": self.min, "max": self.max}
        for tag, q in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
            out[tag] = (data[min(len(data) - 1,
                                 int(q * (len(data) - 1)))]
                        if data else None)
        return out


class _NullMetric:
    """Shared no-op stand-in for every metric type while telemetry is
    disabled — instrumented sites call inc/set/observe unconditionally
    and this absorbs them with zero state."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    @property
    def value(self):
        return 0


NULL_METRIC = _NullMetric()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class MetricRegistry:
    """Process-wide named-metric store.

    Creation is get-or-create keyed by name (one Counter object per
    name for the process lifetime — callers may cache the returned
    object); a name re-requested as a different type raises, because
    two writers disagreeing on a metric's type is always a bug."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, name, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def histogram(self, name, max_samples=512):
        return self._get(name, Histogram, max_samples=max_samples)

    def reset(self):
        """Drop every metric (test isolation)."""
        with self._lock:
            self._metrics.clear()

    def snapshot(self):
        """One coherent host-side snapshot: counters, gauges, and
        histogram stats, stamped with wall time and worker rank.  No
        device access of any kind happens here."""
        with self._lock:
            metrics = list(self._metrics.values())
        counters, gauges, hists = {}, {}, {}
        for m in metrics:
            if isinstance(m, Counter):
                counters[m.name] = m.value
            elif isinstance(m, Gauge):
                gauges[m.name] = m.value
            else:
                hists[m.name] = m.stats()
        try:
            rank = int(os.environ.get("MXTPU_WORKER_RANK", "0") or 0)
        except ValueError:
            rank = 0
        return {"ts": time.time(), "rank": rank,
                "counters": counters, "gauges": gauges,
                "histograms": hists}

    def prometheus_text(self, prefix="mxtpu_"):
        """Prometheus exposition-format text of the current state:
        counters/gauges as-is, histograms as summary ``_count``/
        ``_sum`` plus ``_p50``/``_p99`` quantile gauges.  Every
        metric carries ``# TYPE`` and (where the docs catalog knows
        it) ``# HELP`` — the help text comes from the same
        docs/observability.md tables ci/lint.py already enforces, so
        the exposition and the catalog cannot drift apart."""
        snap = self.snapshot()
        lines = []

        def head(name, kind):
            lines.append(f"# TYPE {prefix}{name} {kind}")
            doc = _metric_help(name)
            if doc:
                lines.append(f"# HELP {prefix}{name} {doc}")

        for name, v in sorted(snap["counters"].items()):
            head(name, "counter")
            lines.append(f"{prefix}{name} {v}")
        for name, v in sorted(snap["gauges"].items()):
            head(name, "gauge")
            lines.append(f"{prefix}{name} {v}")
        for name, st in sorted(snap["histograms"].items()):
            head(name, "summary")
            lines.append(f"{prefix}{name}_count {st['count']}")
            lines.append(f"{prefix}{name}_sum {st['sum']}")
            for q in ("p50", "p99"):
                if st.get(q) is not None:
                    head(f"{name}_{q}", "gauge")
                    lines.append(f"{prefix}{name}_{q} {st[q]}")
        return "\n".join(lines) + "\n"


_HELP_CACHE = {"loaded": False, "help": {}}


def _metric_help(name):
    """Help text for one metric, parsed (once, lazily) from the
    docs/observability.md catalog tables — the single source of
    truth the lint rules enforce metric names against.  Returns None
    when the docs are absent (installed without docs) or the name is
    a derived one (``_p50``/``_p99`` quantiles inherit nothing)."""
    if not _HELP_CACHE["loaded"]:
        _HELP_CACHE["loaded"] = True
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "docs", "observability.md")
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line.startswith("|") or "`" not in line:
                        continue
                    cells = [c.strip() for c in
                             line.strip("|").split("|")]
                    if len(cells) < 3:
                        continue
                    names = re.findall(r"`([^`]+)`", cells[0])
                    text = " ".join(cells[-1].replace("`", "")
                                    .split())
                    for n in names:
                        _HELP_CACHE["help"].setdefault(n, text)
        except OSError:
            pass
    return _HELP_CACHE["help"].get(name)


_REGISTRY = MetricRegistry()


def get_registry():
    return _REGISTRY


def counter(name):
    """Process-wide counter, or the shared no-op when disabled."""
    if not enabled():
        return NULL_METRIC
    return _REGISTRY.counter(name)


def gauge(name):
    if not enabled():
        return NULL_METRIC
    return _REGISTRY.gauge(name)


def histogram(name, max_samples=512):
    if not enabled():
        return NULL_METRIC
    return _REGISTRY.histogram(name, max_samples=max_samples)


def snapshot():
    return _REGISTRY.snapshot()


def prometheus_text():
    return _REGISTRY.prometheus_text()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class _NullSpan:
    """No-op span: the disabled-mode (and re-enterable) singleton."""

    __slots__ = ()
    elapsed = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """Times one wall-clock section into the registry histogram
    ``span_<name>_seconds`` and, when the profiler is running, into
    its chrome://tracing stream (category 'span') so step phases and
    per-op events share a timeline.  Host-side timing only — never
    reads a device value.  The last measured duration stays readable
    as ``.elapsed`` so a fit loop can feed the per-step timeline
    splits to :class:`AnomalyWatch` without re-timing anything."""

    __slots__ = ("name", "_t0", "elapsed")

    def __init__(self, name):
        self.name = name
        self._t0 = None
        self.elapsed = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is None:
            return False
        t1 = time.perf_counter()
        self.elapsed = t1 - self._t0
        _REGISTRY.histogram(
            f"span_{self.name}_seconds").observe(self.elapsed)
        prof = _profiler()
        if prof is not None and prof.running:
            prof.add_event(self.name, self._t0, t1, category="span")
        self._t0 = None
        return False


def _profiler():
    # lazy: profiler.py never imports telemetry at module level, so
    # this direction stays cycle-free; cache after first resolve
    global _PROF
    if _PROF is None:
        from . import profiler as _p
        _PROF = _p._profiler
    return _PROF


_PROF = None


def span(name):
    """``with telemetry.span("data_wait"): ...`` — see :class:`_Span`.
    Returns the shared no-op span when telemetry is disabled."""
    if not enabled():
        return NULL_SPAN
    return _Span(name)


# ---------------------------------------------------------------------------
# emitter
# ---------------------------------------------------------------------------


class TelemetryEmitter:
    """Background flusher: every ``interval`` seconds append one JSONL
    snapshot line to ``path`` (rotated to ``path + '.1'`` past
    ``max_bytes``) and atomically replace the Prometheus textfile
    ``path + '.prom'`` (temp + ``os.replace``, so a scraper never
    reads a torn file).  ``stop()`` performs a final flush so
    short-lived runs still leave a complete record."""

    def __init__(self, path=None, interval=None, registry=None,
                 max_bytes=None):
        self.path = path or get_env("MXTPU_TELEMETRY_FILE") or None
        self.interval = float(
            interval if interval is not None
            else get_env("MXTPU_TELEMETRY_INTERVAL"))
        self.registry = registry or _REGISTRY
        self.max_bytes = int(
            max_bytes if max_bytes is not None
            else get_env("MXTPU_TELEMETRY_MAX_MB") * 1024 * 1024)
        self.flushes = 0
        self._stop = threading.Event()
        self._thread = None
        self._flush_lock = threading.Lock()
        self._atexit = False

    @property
    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        """Spawn the flusher daemon (no-op without a path or when
        telemetry is disabled); returns self.  Registers an atexit
        final flush for THIS emitter: a directly-constructed emitter
        on a short-lived process (bench run, spawned worker) would
        otherwise lose the last partial interval — the daemon thread
        dies with the interpreter mid-wait, never flushing.
        ``stop()`` is idempotent, so an emitter stopped explicitly
        just re-flushes a final complete record at exit."""
        if self.path is None or not enabled() or self.running:
            return self
        if not self._atexit:
            import atexit
            atexit.register(self.stop)
            self._atexit = True
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.flush()
                except OSError:
                    pass    # target dir vanished mid-teardown

        self._thread = threading.Thread(
            target=loop, daemon=True, name="mxtpu-telemetry-emitter")
        self._thread.start()
        return self

    def flush(self):
        """One snapshot -> JSONL append (+rotation) + prom rewrite."""
        if self.path is None:
            return None
        snap = self.registry.snapshot()
        line = json.dumps(snap, sort_keys=True)
        with self._flush_lock:
            self._rotate_if_needed(len(line) + 1)
            with open(self.path, "a") as f:
                f.write(line + "\n")
                f.flush()
            self._write_prom()
            self.flushes += 1
        return snap

    def _rotate_if_needed(self, incoming):
        if self.max_bytes <= 0:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size + incoming > self.max_bytes:
            os.replace(self.path, self.path + ".1")

    def _write_prom(self):
        """Atomic textfile rewrite: a scraper (or a crash) never
        observes a partial exposition.  Reuses resilience's
        mkstemp-based temp+fsync+rename helper — a fixed tmp name
        would collide under concurrent writers and leak on a failed
        serialize (sync_dir=False: freshness-based like heartbeats,
        staleness after power loss is moot)."""
        from . import resilience
        resilience._replace_with_bytes(
            self.path + ".prom",
            self.registry.prometheus_text().encode(), sync_dir=False)

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)
        if self.path is not None and enabled():
            try:
                self.flush()
            except OSError:
                pass


_EMITTER_LOCK = threading.Lock()
_EMITTER = {"obj": None, "atexit": False}


def _emitter_path():
    """Resolve the JSONL target: ``MXTPU_TELEMETRY_FILE``, suffixed
    ``.rank<N>`` for nonzero-rank workers — the launcher exports one
    path to every worker, and concurrent emitters on a shared file
    would race the rotation and tear each other's textfile.  Rank 0
    (and single-process runs) keep the bare path."""
    path = get_env("MXTPU_TELEMETRY_FILE") or None
    if path is None:
        return None
    try:
        rank = int(os.environ.get("MXTPU_WORKER_RANK", "0") or 0)
    except ValueError:
        rank = 0
    return f"{path}.rank{rank}" if rank > 0 else path


def start_emitter(path=None, interval=None):
    """Start the process-wide emitter (idempotent for the same path;
    a new path stops the old emitter and re-targets — the same
    contract as resilience.start_heartbeat).  Registers an atexit
    final flush, so even a run shorter than the flush interval
    leaves a complete JSONL + textfile record.  Returns the emitter,
    or None when disabled / no path configured."""
    if not enabled():
        return None
    path = path or _emitter_path()
    if path is None:
        return None
    with _EMITTER_LOCK:
        cur = _EMITTER["obj"]
        if cur is not None and cur.running:
            if cur.path == path:
                return cur
            cur.stop()
        if not _EMITTER["atexit"]:
            import atexit
            atexit.register(stop_emitter)
            _EMITTER["atexit"] = True
        em = TelemetryEmitter(path=path, interval=interval)
        em.start()
        _EMITTER["obj"] = em
        return em


def maybe_start_emitter():
    """Fit-loop hook: start the emitter iff telemetry is on and
    ``MXTPU_TELEMETRY_FILE`` is set.  Steady-state cost when already
    running (or disabled): an env read and a lock-free check.

    Also the training-side hook for the flight recorder's signal
    dump (no-op unless ``MXTPU_TRACE_DUMP`` is set): fit loops,
    gluon Trainers, and dist.init all pass through here, so a hung
    training worker killed by the launcher leaves a post-mortem just
    like a serving engine does."""
    if not enabled():
        return None
    try:
        from . import tracing
        tracing.install_signal_dump()
    except Exception:
        pass
    cur = _EMITTER["obj"]
    if cur is not None and cur.running and cur.path == _emitter_path():
        return cur
    return start_emitter()


def stop_emitter():
    """Stop the process-wide emitter (final flush included)."""
    with _EMITTER_LOCK:
        em, _EMITTER["obj"] = _EMITTER["obj"], None
    if em is not None:
        em.stop()


# ---------------------------------------------------------------------------
# online anomaly watchdog
# ---------------------------------------------------------------------------


def _median(data):
    """Median of a pre-sorted list."""
    n = len(data)
    mid = n // 2
    if n % 2:
        return data[mid]
    return 0.5 * (data[mid - 1] + data[mid])


class AnomalyWatch:
    """Online regression detector over per-step timeline splits and
    serving latencies (docs/observability.md "Introspection plane").

    Each component (``data_wait`` / ``forward_backward`` /
    ``optimizer`` / ``host_sync``, or serving ``ttft`` /
    ``token_latency``) keeps a rolling window
    (``MXTPU_ANOMALY_WINDOW``) whose median + MAD form the baseline;
    an observation scoring above ``MXTPU_ANOMALY_THRESHOLD`` MADs
    over the median — after ``MXTPU_ANOMALY_MIN_STEPS`` warmup
    samples — opens an **episode**, attributed to the dominant
    drifting component.  Exactly one ``anomaly`` trace event and one
    ``anomaly_detections_total`` increment fire per episode;
    hysteresis (``MXTPU_ANOMALY_COOLDOWN`` consecutive calm samples
    to close) keeps a sustained regression from flapping.  Because
    regressed samples still enter the window, a *permanent* shift
    eventually becomes the new baseline and the episode closes on
    its own — the watchdog flags changes, it does not alarm forever.

    Everything is host-side float arithmetic under one short lock —
    zero device syncs, safe on the step/decode path."""

    def __init__(self, group="train", window=None, threshold=None,
                 min_samples=None, cooldown=None):
        self.group = group
        self.window = int(window if window is not None
                          else get_env("MXTPU_ANOMALY_WINDOW"))
        self.threshold = float(
            threshold if threshold is not None
            else get_env("MXTPU_ANOMALY_THRESHOLD"))
        self.min_samples = int(
            min_samples if min_samples is not None
            else get_env("MXTPU_ANOMALY_MIN_STEPS"))
        self.cooldown = int(cooldown if cooldown is not None
                            else get_env("MXTPU_ANOMALY_COOLDOWN"))
        self.episodes = 0
        self._hist = {}         # component -> deque(maxlen=window)
        self._seen = {}         # component -> total samples fed
        self._open = None       # episode dict while one is open
        self._calm = 0          # consecutive calm samples while open
        self._last_scores = {}
        self._lock = threading.Lock()

    def observe(self, sample):
        """Feed one observation (``{component: seconds}``; partial
        dicts fine — serving feeds ``ttft`` and ``token_latency`` on
        different calls).  Returns the episode dict when this sample
        OPENED one (the caller already got its single emission),
        else None."""
        if not enabled():
            return None
        scores = {}
        with self._lock:
            for comp, v in sample.items():
                v = float(v)
                hist = self._hist.get(comp)
                if hist is None:
                    hist = self._hist[comp] = deque(
                        maxlen=self.window)
                seen = self._seen.get(comp, 0)
                if seen >= self.min_samples and len(hist) >= 2:
                    data = sorted(hist)
                    med = _median(data)
                    mad = _median(sorted(abs(x - med)
                                         for x in data))
                    # noise floor: a near-flat baseline must not
                    # turn scheduler jitter into infinite scores
                    floor = max(mad, 0.05 * abs(med), 1e-9)
                    scores[comp] = ((v - med) / floor, v, med)
                hist.append(v)
                self._seen[comp] = seen + 1
            episode = self._step_episode(scores)
        if episode is not None:
            counter("anomaly_detections_total").inc()
            from . import tracing
            tracing.trace_event(
                "anomaly", group=self.group,
                component=episode["component"],
                score=episode["score"], value=episode["value"],
                median=episode["median"],
                episode=episode["episode"])
        return episode

    def _step_episode(self, scores):
        """Episode state machine (caller holds the lock).  Returns a
        copy of the episode dict exactly when one newly opens."""
        self._last_scores = {c: round(s[0], 3)
                             for c, s in scores.items()}
        hot = {c: s for c, s in scores.items()
               if s[0] >= self.threshold}
        if self._open is None:
            if not hot:
                return None
            comp = max(hot, key=lambda c: hot[c][0])
            score, value, med = hot[comp]
            self.episodes += 1
            self._calm = 0
            self._open = {"component": comp,
                          "score": round(score, 3), "value": value,
                          "median": med, "episode": self.episodes,
                          "samples": 1}
            return dict(self._open)
        self._open["samples"] += 1
        if hot:
            self._calm = 0
            comp = max(hot, key=lambda c: hot[c][0])
            if hot[comp][0] > self._open["score"]:
                # attribution tracks the dominant drifting component
                self._open.update(
                    component=comp, score=round(hot[comp][0], 3),
                    value=hot[comp][1], median=hot[comp][2])
        else:
            self._calm += 1
            if self._calm >= self.cooldown:
                self._open = None
                self._calm = 0
        return None

    def verdicts(self):
        """Host-side verdict snapshot for ``healthz``."""
        with self._lock:
            return {"group": self.group,
                    "anomalous": self._open is not None,
                    "episodes": self.episodes,
                    "open": dict(self._open) if self._open else None,
                    "scores": dict(self._last_scores)}


_ANOMALY_LOCK = threading.Lock()
_ANOMALY = {}


def anomaly_watch(group="train"):
    """Process-wide get-or-create :class:`AnomalyWatch` per feed
    group (``train`` step splits, ``serving`` latency feeds)."""
    with _ANOMALY_LOCK:
        w = _ANOMALY.get(group)
        if w is None:
            w = _ANOMALY[group] = AnomalyWatch(group=group)
        return w


def anomaly_verdicts():
    """Every group's verdicts (for ``healthz``); {} when nothing has
    been fed yet."""
    with _ANOMALY_LOCK:
        watches = list(_ANOMALY.values())
    return {w.group: w.verdicts() for w in watches}


def reset_anomaly_for_tests():
    """Drop all watch state (test isolation)."""
    with _ANOMALY_LOCK:
        _ANOMALY.clear()


# ---------------------------------------------------------------------------
# heartbeat ride-along
# ---------------------------------------------------------------------------


def heartbeat_payload():
    """Compact one-line JSON snapshot appended to the per-worker
    heartbeat file by ``resilience._beat`` (line 1 stays the bare
    timestamp, so mtime-based monitors and old parsers are
    untouched).  ``tools/launch.py`` reads these to aggregate ranks.
    Empty string when telemetry is disabled.

    Each beat first refreshes the tracing layer's memory gauges
    (host RSS + device live/peak bytes attributed to params /
    optimizer / KV pools / workspace — metadata reads only, no
    device syncs), so per-rank memory and the compile-event counters
    ride the same channel launch.py already monitors."""
    if not enabled():
        return ""
    try:
        from . import tracing
        tracing.update_memory_gauges()
    except Exception:
        pass    # memory sampling must never silence the heartbeat
    return json.dumps(_REGISTRY.snapshot(), sort_keys=True)
