"""Contrib namespace: experimental / detection operators.

Mirrors the reference's ``mx.contrib.ndarray`` / ``mx.contrib.symbol``
surface (ref: python/mxnet/contrib/__init__.py), which exposes the
``_contrib_*`` registry entries without their prefix, e.g.
``mx.contrib.nd.MultiBoxPrior``.
"""
import types as _types

from ..ops.registry import OPS as _OPS

__all__ = ["ndarray", "nd", "symbol", "sym"]


def _make_namespace(modname, lookup):
    m = _types.ModuleType(modname)
    for name, op in list(_OPS.items()):
        if name.startswith("_contrib_"):
            short = name[len("_contrib_"):]
            fn = lookup(name)
            if fn is not None:
                setattr(m, short, fn)
    return m


def _nd_lookup(name):
    from .. import ndarray as _nd
    return getattr(_nd._internal, name, None)


def _sym_lookup(name):
    from .. import symbol as _sym
    return getattr(_sym._internal, name, None)


ndarray = _make_namespace(__name__ + ".ndarray", _nd_lookup)
nd = ndarray
symbol = _make_namespace(__name__ + ".symbol", _sym_lookup)
sym = symbol

# reference parity: the contrib ops are reachable both ways —
# mx.contrib.nd.X and mx.nd.contrib.X (ref: python/mxnet/ndarray/
# contrib.py / symbol/contrib.py)
def _attach():
    import sys as _sys
    from .. import ndarray as _nd
    from .. import symbol as _sym
    _nd.contrib = ndarray
    _sym.contrib = symbol
    # `import incubator_mxnet_tpu.ndarray.contrib` must work as a
    # statement too, like the reference's real submodules
    _sys.modules[_nd.__name__ + ".contrib"] = ndarray
    _sys.modules[_sym.__name__ + ".contrib"] = symbol


_attach()
