"""Legacy experimental autograd namespace (ref:
python/mxnet/contrib/autograd.py — the pre-1.0 experimental API the
reference kept alongside ``mx.autograd``).

Everything here is the core tape under the old names:
``train_section``/``test_section`` context managers and
``compute_gradient``; new code should use ``mx.autograd``.
"""
from ..autograd import (record as train_section,          # noqa: F401
                        pause as test_section,
                        backward,
                        mark_variables,
                        grad)

__all__ = ["train_section", "test_section", "backward",
           "mark_variables", "grad_and_loss", "grad",
           "compute_gradient"]


def compute_gradient(outputs):
    """Legacy spelling of ``backward(outputs)``."""
    return backward(outputs)


def grad_and_loss(func, argnum=None):
    """Decorator: ``f(*args) -> (grads, outputs)`` (the legacy
    experimental API's shape — ref contrib/autograd.py
    grad_and_loss)."""
    import functools

    from .. import nd as _nd
    from ..autograd import record as _record

    def _as_list(x):
        return list(x) if isinstance(x, (list, tuple)) else [x]

    @functools.wraps(func)
    def wrapped(*args):
        sel = _as_list(argnum) if argnum is not None \
            else list(range(len(args)))
        variables = [args[i] for i in sel]
        for v in variables:
            v.attach_grad()
        with _record():
            outputs = func(*args)
            head = outputs[0] if isinstance(
                outputs, (list, tuple)) else outputs
            total = _nd.sum(head) if head.ndim else head
        total.backward()
        grads = [v.grad for v in variables]
        return grads, outputs
    return wrapped
