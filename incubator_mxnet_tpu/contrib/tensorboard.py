"""TensorBoard bridge (ref: python/mxnet/contrib/tensorboard.py —
``LogMetricsCallback``, which streams EvalMetric values into a
summary writer so training curves show up in TensorBoard).

Writer resolution order:
1. an explicit ``summary_writer`` object (anything with
   ``add_scalar(tag, value, step)``),
2. ``torch.utils.tensorboard.SummaryWriter`` (torch-cpu ships in
   this image) writing real TF event files,
3. a JSONL fallback writing ``{"tag", "value", "step"}`` lines —
   zero-dependency, parseable by ``tools/parse_log.py`` style
   tooling.
"""
import json
import os
import time

__all__ = ["LogMetricsCallback", "make_writer", "log_telemetry"]


class _JsonlWriter:
    """Dependency-free event log: one JSON object per scalar."""

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(
            logdir, f"events.{int(time.time())}.jsonl")
        self._f = open(self._path, "a")

    def add_scalar(self, tag, value, step):
        self._f.write(json.dumps(
            {"tag": tag, "value": float(value), "step": int(step),
             "ts": time.time()}) + "\n")
        self._f.flush()

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


def make_writer(logdir):
    """Best available summary writer for ``logdir``."""
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(logdir)
    except Exception:
        return _JsonlWriter(logdir)


def log_telemetry(writer, snapshot=None, step=None):
    """Write a telemetry registry snapshot's gauges (and counters) as
    TensorBoard scalars, tagged ``telemetry/<name>``.

    ``snapshot`` defaults to a fresh ``telemetry.snapshot()``;
    ``step`` defaults to the snapshot's ``train_steps_total`` counter
    so successive calls land on the training-step axis.  Returns the
    number of scalars written — 0 with telemetry disabled."""
    from .. import telemetry
    if snapshot is None:
        if not telemetry.enabled():
            return 0
        snapshot = telemetry.snapshot()
    if step is None:
        step = int(snapshot.get("counters", {})
                   .get("train_steps_total", 0))
    written = 0
    for name, value in sorted(snapshot.get("gauges", {}).items()):
        writer.add_scalar(f"telemetry/{name}", value, step)
        written += 1
    for name, value in sorted(snapshot.get("counters", {}).items()):
        writer.add_scalar(f"telemetry/{name}", value, step)
        written += 1
    return written


class LogMetricsCallback:
    """Batch-end callback streaming metric values to a writer.

    >>> cb = LogMetricsCallback('./logs', prefix='train')
    >>> mod.fit(it, batch_end_callback=cb, ...)
    >>> cb.close()          # or: with LogMetricsCallback(...) as cb:

    Same call contract as the reference's: invoked with a
    ``BatchEndParam``-style object carrying ``epoch``, ``nbatch``
    and ``eval_metric``.  Owns the writer it creates (closing it on
    close()/exit releases the underlying fd); an explicitly passed
    ``summary_writer`` stays the caller's to close.
    """

    def __init__(self, logging_dir, prefix=None,
                 summary_writer=None):
        self.prefix = prefix
        self.step = 0
        self._owns_writer = summary_writer is None
        self.writer = summary_writer or make_writer(logging_dir)

    def __call__(self, param):
        if self.writer is None:
            raise ValueError(
                "LogMetricsCallback was closed; create a new one "
                "for further logging")
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in self._pairs(param.eval_metric):
            tag = f"{self.prefix}-{name}" if self.prefix else name
            self.writer.add_scalar(tag, value, self.step)

    def close(self):
        w, self.writer = self.writer, None
        if w is not None and self._owns_writer and \
                hasattr(w, "close"):
            w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @staticmethod
    def _pairs(metric):
        name, value = metric.get()
        if not isinstance(name, list):
            name, value = [name], [value]
        return list(zip(name, value))
