"""Executor: compiles a bound Symbol graph into XLA executables.

Role analog of GraphExecutor (ref: src/executor/graph_executor.cc
Init:517, RunOps:1445; include/mxnet/executor.h).  Where the
reference pre-creates one engine op per node (InitCachedOps:1226) and
plans a shared memory pool (PlanMemory), here the *entire* graph —
and for training the fused forward+backward — is traced once and
handed to XLA as a single jit-compiled executable: fusion, buffer
reuse, scheduling and async execution all come from the compiler.
This is the direct TPU analog of the reference's bulk-exec mode
(MXNET_EXEC_BULK_EXEC_TRAIN) taken to its limit.

Recompilation on new input signatures is automatic via jax.jit's
shape-keyed cache — the CachedOp pattern (ref:
src/imperative/cached_op.cc GetForwardGraph:171).
"""
import numpy as np

import jax
import jax.numpy as jnp

from . import random_state
from .base import np_dtype
from .context import default_context
from .ndarray.ndarray import NDArray
from .symbol.symbol import _topo

__all__ = ["Executor", "build_graph_fn"]

# once-per-process notice when a partial last batch is padded
_PARTIAL_WARNED = False


def build_graph_fn(symbol, placements=None, default_device=None,
                   tap=None):
    """Build the pure evaluation function of a Symbol graph.

    Returns fn(arg_vals: dict, aux_vals: dict, rng, is_train) ->
    (outputs: list, aux_updates: dict) suitable for jax.jit
    (is_train static).

    ``placements`` (id(node) -> jax.Device) activates multi-device
    placement — the TPU-native reading of the reference's PlaceDevice
    pass (ref: src/executor/graph_executor.cc:411): each node's inputs
    are ``jax.device_put`` to its group's device (the _CrossDeviceCopy
    analog; differentiable, so vjp replays transfers in reverse), and
    the node's eager op then executes there.  Placed graphs must run
    UN-jitted (explicit per-device transfer is not expressible inside
    a single-device jit trace).

    ``tap(name, outputs)`` is the monitor hook (ref:
    graph_executor.cc:121 monitor_callback): called after every
    non-variable node with its output arrays.  Tapped graphs also run
    un-jitted — per-op visibility is a debugging mode, fusion is
    deliberately off.
    """
    order = _topo(symbol._heads)
    heads = list(symbol._heads)

    def run(arg_vals, aux_vals, rng, is_train):
        env = {}
        aux_updates = {}
        rng_counter = 0
        for node in order:
            if node.is_variable:
                if node.name in arg_vals:
                    env[(id(node), 0)] = arg_vals[node.name]
                elif node.name in aux_vals:
                    env[(id(node), 0)] = aux_vals[node.name]
                else:
                    raise KeyError(
                        f"unbound variable '{node.name}'")
                continue
            op = node.op
            ins = [env[(id(n), i)] for n, i in node.inputs]
            if placements is not None:
                dev = placements.get(id(node), default_device)
                ins = [jax.device_put(x, dev) for x in ins]
            params = dict(node.params)
            if op.needs_mode:
                params["_training"] = is_train
            if op.needs_rng:
                # optimized graphs pin each rng node's fold index at
                # its pre-optimization position (__rng_index__, see
                # graph.passes.stamp_rng_indices) so rewrites that
                # remove neighbours never shift the key stream
                idx = node.attrs.get("__rng_index__")
                fold = int(idx) if idx is not None else rng_counter
                params["_rng"] = jax.random.fold_in(rng, fold)
                rng_counter += 1
            outs = op.fn(*ins, **params)
            outs_list = list(outs) if isinstance(outs, (tuple, list)) \
                else [outs]
            if op.num_aux and is_train:
                aux_new = outs_list[-op.num_aux:]
                outs_list = outs_list[:-op.num_aux]
                aux_nodes = node.inputs[-op.num_aux:]
                for (anode, _), val in zip(aux_nodes, aux_new):
                    aux_updates[anode.name] = val
            if tap is not None:
                tap(node.name, outs_list)
            for i, o in enumerate(outs_list):
                env[(id(node), i)] = o
        outputs = [env[(id(n), i)] for n, i in heads]
        return outputs, aux_updates

    return run


def _ones_ct(o):
    if jnp.issubdtype(o.dtype, jnp.floating):
        return jnp.ones(o.shape, o.dtype)
    return np.zeros(o.shape, jax.dtypes.float0)


def _scan_ctx_groups(symbol, group2ctx):
    """Validate group2ctx and resolve it against the graph.

    Returns (placements, var_ctx): ``placements`` maps id(op node) ->
    jax.Device for every node whose ``ctx_group`` attr names a mapped
    group; ``var_ctx`` maps variable name -> Context for allocation.
    """
    for g, c in group2ctx.items():
        if not hasattr(c, "jax_device"):
            raise TypeError(
                f"group2ctx[{g!r}] must be a Context, got "
                f"{type(c).__name__}")
    placements, var_ctx = {}, {}
    for node in _topo(symbol._heads):
        grp = node.attrs.get("ctx_group")
        if grp is None or grp not in group2ctx:
            continue
        if node.is_variable:
            var_ctx[node.name] = group2ctx[grp]
        else:
            placements[id(node)] = group2ctx[grp].jax_device
    return placements, var_ctx


class Executor:
    """A bound, compiled computation graph
    (ref: include/mxnet/executor.h Forward/Backward)."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None, shared_exec=None,
                 group2ctx=None, _ctx_group_scan=None):
        self._symbol = symbol
        self._ctx = ctx or default_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        self.arg_dict = self._normalize(args, arg_names, "args")
        self.aux_dict = self._normalize(aux_states, aux_names,
                                        "aux_states", allow_empty=True)
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in arg_names}
        if args_grad is None:
            args_grad = {}
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        self.grad_dict = {
            n: args_grad.get(n) for n in arg_names
            if self._grad_req.get(n, "null") != "null"
            and args_grad.get(n) is not None}

        # group2ctx placement (ref: graph_executor.cc PlaceDevice:411):
        # map each node's ctx_group attribute onto a concrete device.
        # Placed execution skips whole-graph jit (see build_graph_fn);
        # groups absent from group2ctx fall back to the bind ctx, and
        # an all-same-device mapping degenerates to the fast jit path.
        self._group2ctx = dict(group2ctx) if group2ctx else None
        self._placed = False
        self._out_ctx = None
        placements = None
        if group2ctx:
            placements, var_ctx = _ctx_group_scan or \
                _scan_ctx_groups(symbol, group2ctx)
            default_dev = self._ctx.jax_device
            # variable-only tags still force placed (eager) execution:
            # their arrays are committed to group devices, which a
            # single-device jit would reject as incompatible inputs
            if any(d != default_dev for d in placements.values()) or \
                    any(c.jax_device != default_dev
                        for c in var_ctx.values()):
                self._placed = True
                # outputs carry the context of the head node's group
                # (reference: outputs live on their group's ctx)
                self._out_ctx = [
                    group2ctx.get(n.attrs.get("ctx_group"), self._ctx)
                    for n, _ in symbol._heads]
            else:
                placements = None       # degenerate: single device

        # graph-optimization pass pipeline (graph/, ROADMAP item 4):
        # every non-placed bind routes the traced graph through the
        # PassManager under MXTPU_GRAPH_OPT before compilation.
        # Placed (group2ctx) graphs keep their original nodes — the
        # placement map is keyed on node identity.  self._symbol
        # stays the ORIGINAL symbol: listings, shape inference,
        # reshape and the monitor tap all see the user's graph.
        self.graph_report = None
        run_symbol = symbol
        if not self._placed:
            from .graph.passes import optimize_symbol
            run_symbol, self.graph_report = optimize_symbol(symbol)
        self._run = build_graph_fn(
            run_symbol,
            placements=placements if self._placed else None,
            default_device=self._ctx.jax_device if self._placed
            else None)
        self._placements = placements if self._placed else None
        self._monitor_cb = None
        self._run_tapped = None
        self._jit_fwd = {}
        self._jit_fwd_bwd = {}
        self._outputs = None
        self._last_rng = None
        self._batch_row_outputs = {}    # batch -> pad/slice is exact

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _normalize(vals, names, what, allow_empty=False):
        if vals is None:
            if allow_empty:
                return {}
            raise ValueError(f"{what} must be provided to bind")
        if isinstance(vals, (list, tuple)):
            if len(vals) != len(names):
                raise ValueError(
                    f"{what}: expected {len(names)} entries "
                    f"({names}), got {len(vals)}")
            return dict(zip(names, vals))
        return dict(vals)

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n]
                for n in self._symbol.list_auxiliary_states()]

    @property
    def outputs(self):
        if self._outputs is None:
            raise RuntimeError("call forward() first")
        return self._outputs

    @property
    def output_shapes(self):
        _, out_shapes, _ = self._symbol.infer_shape(
            **{k: v.shape for k, v in self.arg_dict.items()})
        return out_shapes

    def _jvals(self, d):
        return {k: v._data for k, v in d.items() if v is not None}

    # ------------------------------------------------------------- forward
    def _get_fwd(self, is_train):
        if is_train not in self._jit_fwd:
            run = self._run

            def f(arg_vals, aux_vals, rng):
                return run(arg_vals, aux_vals, rng, is_train)
            self._jit_fwd[is_train] = f if self._placed else jax.jit(f)
        return self._jit_fwd[is_train]

    def _set_inputs(self, kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise ValueError(
                    f"unknown argument '{k}'; bound arguments are "
                    f"{sorted(self.arg_dict)}")
            if isinstance(v, NDArray):
                self.arg_dict[k]._data = v._data.astype(
                    self.arg_dict[k]._data.dtype)
            else:
                self.arg_dict[k]._data = jnp.asarray(
                    v, self.arg_dict[k]._data.dtype)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Per-op output tap for debugging (ref:
        MXExecutorSetMonitorCallback, graph_executor.cc:121).

        While set, ``forward`` evaluates the graph eagerly un-jitted
        and calls ``callback(op_name, [NDArray, ...])`` after every
        node — full per-op visibility at debugging (not production)
        speed.  Pass ``None`` to restore the fused executable.
        """
        if callback is None:
            self._monitor_cb = None
            self._run_tapped = None
            return

        def tapped(name, outs):
            self._monitor_cb(name, [NDArray(o, self._ctx)
                                    for o in outs])

        from .utils.log import get_logger
        get_logger().warning(
            "Monitor armed: forward now runs un-jitted per-op tapped "
            "evaluation (orders of magnitude slower than the fused "
            "executable). Debug only; call set_monitor_callback(None) "
            "/ Monitor uninstall to restore compiled speed.")
        self._monitor_cb = callback
        self._run_tapped = build_graph_fn(
            self._symbol, placements=self._placements,
            default_device=self._ctx.jax_device if self._placements
            else None, tap=tapped)

    def forward(self, is_train=False, **kwargs):
        """Run forward; returns output NDArrays
        (ref: graph_executor.cc Forward:81).

        A PARTIAL LAST BATCH — an input whose leading dimension is
        smaller than the bound batch size — is padded up to the
        bound shape and the outputs are sliced back, so the one
        compiled executable serves every tail batch instead of
        failing on baked shapes (or recompiling per size).  Padding
        only engages when EVERY output carries the batch as its
        leading dimension — a graph that reduces over the batch axis
        (a mean loss head) would silently average the padded rows,
        so such graphs keep the exact-shape behavior."""
        n_partial = self._pad_partial(kwargs)
        self._set_inputs(kwargs)
        rng = random_state.next_key()
        self._last_rng = rng
        if self._run_tapped is not None:    # monitor debugging mode
            outs, aux_upd = self._run_tapped(
                self._jvals(self.arg_dict), self._jvals(self.aux_dict),
                rng, bool(is_train))
        else:
            outs, aux_upd = self._get_fwd(bool(is_train))(
                self._jvals(self.arg_dict), self._jvals(self.aux_dict),
                rng)
        for name, val in aux_upd.items():
            self.aux_dict[name]._data = val
        self._outputs = self._wrap_outputs(outs)
        if n_partial is not None:
            batch = self._partial_bound_batch
            self._outputs = [
                NDArray(o._data[:n_partial], o._ctx)
                if o.shape and o.shape[0] == batch else o
                for o in self._outputs]
        return self._outputs

    _partial_bound_batch = None

    def _outputs_are_batch_rowed(self, batch):
        """True iff every graph output's leading dim is ``batch`` —
        the precondition for pad/slice to be exact (a padded row
        must never fold into a real row's value, which a
        batch-reducing output would)."""
        cached = self._batch_row_outputs.get(batch)
        if cached is None:
            try:
                shapes = self.output_shapes
            except Exception:
                shapes = None
            cached = bool(shapes) and all(
                s and s[0] == batch for s in shapes)
            self._batch_row_outputs[batch] = cached
        return cached

    def _pad_partial(self, kwargs):
        """Pad partial-last-batch inputs to the bound batch size;
        returns the true row count (or None when nothing padded)."""
        n = None
        bound_batch = None
        partial = []
        for k, v in kwargs.items():
            bound = self.arg_dict.get(k)
            if bound is None:
                continue                # _set_inputs raises clearly
            # shape probe without any device->host transfer; only a
            # genuinely partial input is materialized for padding
            vshape = tuple(v.shape) if hasattr(v, "shape") \
                else np.asarray(v).shape
            bshape = bound.shape
            if vshape == bshape or len(vshape) != len(bshape) \
                    or not bshape:
                continue
            if vshape[1:] == bshape[1:] and vshape[0] < bshape[0]:
                if n is not None and n != vshape[0]:
                    raise ValueError(
                        "partial batch sizes disagree across "
                        f"inputs ({n} vs {vshape[0]} for {k!r})")
                n = vshape[0]
                bound_batch = bshape[0]
                partial.append(k)
        if n is None or not self._outputs_are_batch_rowed(
                bound_batch):
            # batch-reducing (or shapeless) outputs: keep the exact
            # old behavior — recompile at the true shape, or fail
            # loudly on baked shapes — rather than silently folding
            # padded rows into a reduction
            return None
        for k in partial:
            v = kwargs[k]
            arr = v.asnumpy() if isinstance(v, NDArray) \
                else np.asarray(v)
            pad = np.zeros(self.arg_dict[k].shape, arr.dtype)
            pad[:n] = arr
            kwargs[k] = pad
        self._partial_bound_batch = bound_batch
        global _PARTIAL_WARNED
        if not _PARTIAL_WARNED:
            from .utils.log import get_logger
            get_logger().warning(
                "partial batch of %d rows padded to the bound "
                "batch %d (outputs sliced back; reported once)",
                n, bound_batch)
            _PARTIAL_WARNED = True
        return n

    def _wrap_outputs(self, outs):
        ctxs = self._out_ctx or [self._ctx] * len(outs)
        return [NDArray(o, c) for o, c in zip(outs, ctxs)]

    # ------------------------------------------------------------- backward
    def _grad_names(self):
        return [n for n in self._symbol.list_arguments()
                if self._grad_req.get(n, "null") != "null"
                and self.grad_dict.get(n) is not None]

    def _get_fwd_bwd(self, with_head_grads):
        key = with_head_grads
        if key not in self._jit_fwd_bwd:
            run = self._run
            grad_names = tuple(self._grad_names())

            def f(arg_vals, aux_vals, rng, head_cts):
                others = {k: v for k, v in arg_vals.items()
                          if k not in grad_names}

                def inner(gvals):
                    merged = dict(others)
                    merged.update(zip(grad_names, gvals))
                    outs, aux_upd = run(merged, aux_vals, rng, True)
                    return outs, aux_upd

                primals = tuple(arg_vals[n] for n in grad_names)
                (outs, aux_upd), vjp = jax.vjp(inner, primals)
                if head_cts is None:
                    cts = [_ones_ct(o) for o in outs]
                else:
                    cts = [c if c is not None else _ones_ct(o)
                           for c, o in zip(head_cts, outs)]
                aux_ct = {k: (np.zeros(v.shape, jax.dtypes.float0)
                              if not jnp.issubdtype(v.dtype, jnp.floating)
                              else jnp.zeros(v.shape, v.dtype))
                          for k, v in aux_upd.items()}
                (gvals,) = vjp((cts, aux_ct))
                return outs, aux_upd, dict(zip(grad_names, gvals))

            if with_head_grads:
                self._jit_fwd_bwd[key] = \
                    f if self._placed else jax.jit(f)
            else:
                g = lambda a, x, r: f(a, x, r, None)
                self._jit_fwd_bwd[key] = \
                    g if self._placed else jax.jit(g)
        return self._jit_fwd_bwd[key]

    def backward(self, out_grads=None):
        """Compute gradients into grad arrays honoring grad_req
        (ref: graph_executor.cc Backward:94).  Fused with a forward
        replay in one XLA executable; prefer forward_backward() in
        training loops to avoid the separate forward."""
        self.forward_backward(out_grads=out_grads, _refresh_outputs=False)

    def forward_backward(self, out_grads=None, _refresh_outputs=True,
                         **kwargs):
        """One fused XLA call computing outputs + all gradients —
        the hot training path (bulk-exec analog)."""
        self._set_inputs(kwargs)
        rng = self._last_rng if not _refresh_outputs and \
            self._last_rng is not None else random_state.next_key()
        self._last_rng = rng
        args_j = self._jvals(self.arg_dict)
        aux_j = self._jvals(self.aux_dict)
        if self._run_tapped is not None and _refresh_outputs:
            # monitor debugging mode: one eager tapped forward for the
            # per-op rows (tapping inside the vjp trace would hand the
            # stat fn tracers); the real step below stays fused.  The
            # backward() path (_refresh_outputs=False) reuses the rng
            # of a tapped forward that already streamed these rows.
            self._run_tapped(args_j, aux_j, rng, True)
        if out_grads is not None:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = [g._data if isinstance(g, NDArray) else g
                   for g in out_grads]
            outs, aux_upd, grads = self._get_fwd_bwd(True)(
                args_j, aux_j, rng, cts)
        else:
            outs, aux_upd, grads = self._get_fwd_bwd(False)(
                args_j, aux_j, rng)
        for name, val in aux_upd.items():
            self.aux_dict[name]._data = val
        for name, g in grads.items():
            buf = self.grad_dict.get(name)
            if buf is None:
                continue
            if self._grad_req.get(name) == "add":
                buf._data = buf._data + g
            else:
                buf._data = g
        self._outputs = self._wrap_outputs(outs)
        return self._outputs

    # ------------------------------------------------------------- misc
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data.astype(
                    self.arg_dict[k]._data.dtype)
            elif not allow_extra_params:
                raise ValueError(f"unknown argument {k}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._data = v._data.astype(
                    self.aux_dict[k]._data.dtype)
            elif not allow_extra_params:
                raise ValueError(f"unknown aux state {k}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Rebind with new input shapes; XLA recompiles lazily via the
        shape-keyed jit cache, so this is just buffer reallocation."""
        shapes = {k: v.shape for k, v in self.arg_dict.items()}
        shapes.update(kwargs)
        # preserve bound dtypes (int inputs, fp16/bf16 bindings)
        type_dict = {k: v.dtype for k, v in self.arg_dict.items()}
        type_dict.update({k: v.dtype for k, v in self.aux_dict.items()})
        return Executor._simple_bind(
            self._symbol, self._ctx,
            self._grad_req, type_dict, shapes, _copy_from=self,
            group2ctx=self._group2ctx)

    @classmethod
    def _simple_bind(cls, symbol, ctx, grad_req, type_dict, shape_kwargs,
                     _copy_from=None, group2ctx=None):
        """Allocate all arrays from inferred shapes and bind
        (ref: MXExecutorSimpleBind, c_api_executor.cc:220)."""
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape_kwargs)
        arg_names = symbol.list_arguments()
        if len(set(arg_names)) != len(arg_names):
            dupes = sorted({n for n in arg_names
                            if arg_names.count(n) > 1})
            raise ValueError(
                f"duplicate argument names {dupes}: distinct "
                "variables share a name (a scoped NameManager can "
                "restart counters mid-graph) — disambiguate with "
                "name=/mx.name.Prefix scopes")
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        # with group2ctx, variables tagged ctx_group get their arrays
        # allocated on (and committed to) the group's device, matching
        # the reference's per-group arg allocation
        var_ctx, scan = {}, None
        if group2ctx:
            scan = _scan_ctx_groups(symbol, group2ctx)
            var_ctx = scan[1]

        def _alloc(n, s, dt):
            c = var_ctx.get(n, ctx)
            buf = jnp.zeros(s, dt)
            if n in var_ctx:
                buf = jax.device_put(buf, c.jax_device)
            return NDArray(buf, c)

        args = {}
        for n, s in zip(arg_names, arg_shapes):
            args[n] = _alloc(n, s, np_dtype(type_dict.get(n, "float32")))
        aux = {}
        for n, s in zip(aux_names, aux_shapes):
            aux[n] = _alloc(n, s, np_dtype(type_dict.get(n, "float32")))
        if isinstance(grad_req, str):
            req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            req = dict(zip(arg_names, grad_req))
        else:
            req = dict(grad_req)
        grads = {n: NDArray(jnp.zeros_like(args[n]._data),
                            var_ctx.get(n, ctx))
                 for n in arg_names if req.get(n, "null") != "null"}
        ex = cls(symbol, ctx, args, grads, req, aux,
                 group2ctx=group2ctx, _ctx_group_scan=scan)
        if _copy_from is not None:
            for k, v in _copy_from.arg_dict.items():
                if k in ex.arg_dict and v.shape == ex.arg_dict[k].shape:
                    ex.arg_dict[k]._data = v._data
            for k, v in _copy_from.aux_dict.items():
                if k in ex.aux_dict and v.shape == ex.aux_dict[k].shape:
                    ex.aux_dict[k]._data = v._data
        return ex
