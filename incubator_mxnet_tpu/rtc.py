"""Runtime custom-kernel registration — the TPU answer to ``mx.rtc``.

The reference lets users hand the runtime raw CUDA source and call it
as a kernel (ref: python/mxnet/rtc.py:1 CudaModule/get_kernel,
include/mxnet/rtc.h:136).  On TPU the user-extensible kernel layer is
**Pallas**: you write a Python kernel over VMEM refs, Mosaic compiles
it for the systolic array, and here it becomes a first-class operator
— visible from ``nd`` (eager), ``sym`` (graphs), and any Gluon
``HybridBlock``, differentiable if you give it a VJP, and fused into
jit-compiled executables like every built-in op.

Two layers:

``compile_kernel``
    pallas_call wrapper with interpret-mode auto-detection (the
    kernel runs through the Pallas interpreter off-TPU, so custom
    kernels are testable on CPU and in CI).

``register``
    put any jit-compatible function — a compiled Pallas kernel or
    plain jax.numpy — into the central op registry and onto the
    nd/sym namespaces.

Example (see examples/custom_pallas_kernel.py and tests/test_rtc.py)::

    from jax.experimental import pallas as pl

    def scale_kernel(x_ref, o_ref, *, alpha):
        o_ref[...] = x_ref[...] * alpha

    fn = rtc.compile_kernel(
        scale_kernel,
        out_shape=lambda x, alpha=2.0: jax.ShapeDtypeStruct(
            x.shape, x.dtype))
    rtc.register("my_scale", fn,
                 vjp=(lambda x, alpha=2.0: (fn(x, alpha=alpha), None),
                      lambda alpha, res, g: (g * alpha,)))

    y = mx.nd.my_scale(mx.nd.ones((4, 4)), alpha=3.0)   # eager
    s = mx.sym.my_scale(mx.sym.Variable("x"), alpha=3.0)  # symbolic
"""
import functools

import jax

from .ops.registry import OPS, OpDef

__all__ = ["compile_kernel", "register", "on_tpu"]


def on_tpu():
    """True when the default jax backend is a real accelerator."""
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def compile_kernel(kernel, out_shape, *, interpret=None,
                   grid=None, in_specs=None, out_specs=None,
                   **pallas_kwargs):
    """Wrap a Pallas kernel into a jit-compatible callable.

    Parameters
    ----------
    kernel : Pallas kernel ``fn(*in_refs, *out_refs, **params)``.
        Static params are forwarded from the call site by keyword.
    out_shape : ``jax.ShapeDtypeStruct`` (or list of them), or a
        callable ``(*arrays, **params) -> out_shape`` evaluated per
        call — shape polymorphism the CUDA-RTC analog never had.
    interpret : force Pallas interpret mode.  Default ``None`` =
        auto: compiled on TPU, interpreted elsewhere (CPU testing).
    grid, in_specs, out_specs, **pallas_kwargs :
        forwarded to ``pallas_call`` (same semantics; may each be a
        callable of ``(*arrays, **params)`` for shape-dependent
        tiling).
    """
    from jax.experimental import pallas as pl

    def call(*arrays, **params):
        ipret = params.pop("_interpret", interpret)
        if ipret is None:
            ipret = not on_tpu()

        def resolve(v):
            return v(*arrays, **params) if callable(v) else v

        kw = dict(pallas_kwargs)
        for k, v in (("grid", grid), ("in_specs", in_specs),
                     ("out_specs", out_specs)):
            if v is not None:
                kw[k] = resolve(v)
        bound = functools.partial(kernel, **params) if params \
            else kernel
        return pl.pallas_call(
            bound, out_shape=resolve(out_shape), interpret=ipret,
            **kw)(*arrays)

    call.__name__ = getattr(kernel, "__name__", "pallas_kernel")
    call.__doc__ = kernel.__doc__
    return call


def register(name, fn, *, vjp=None, arg_names=None,
             differentiable=None, num_outputs=1, aliases=(),
             **opdef_kwargs):
    """Register ``fn`` as operator ``name`` on nd/sym/Gluon surfaces.

    Parameters
    ----------
    fn : jit-compatible ``(*jnp_arrays, **static_params) -> array(s)``
        — typically the result of :func:`compile_kernel`.
    vjp : optional ``(fwd, bwd)`` pair giving the op a custom
        gradient (``jax.custom_vjp`` convention):
        ``fwd(*arrays, **params) -> (out, residuals)`` and
        ``bwd(*param_values, residuals, cotangent) -> grads`` where
        param_values are the op's static params in sorted-name order.
        Without a vjp the op differentiates through ``fn`` itself if
        possible (fine for plain-jax fns; Pallas kernels usually
        need one).
    arg_names : tensor input names for the symbolic frontend
        (defaults to fn's positional signature).
    aliases : extra registry names.

    Returns the eager (``nd``) function.
    """
    if name in OPS:
        raise ValueError(
            f"op '{name}' already exists; rtc.register cannot "
            "shadow a built-in or an earlier custom kernel")
    clashes = [a for a in aliases if a in OPS]
    if clashes:            # validate BEFORE mutating the registry
        raise ValueError(f"aliases {clashes} conflict with existing ops")
    if vjp is not None:
        vjp_fwd, vjp_bwd = vjp
        base = fn
        # static-param defaults come from the fwd rule's signature, so
        # the bwd rule sees the SAME param values whether the caller
        # passed them or relied on defaults
        import inspect
        try:
            fwd_defaults = {
                p.name: p.default
                for p in inspect.signature(vjp_fwd).parameters.values()
                if p.default is not p.empty}
        except (TypeError, ValueError):
            fwd_defaults = {}
        vjp_cache = {}   # params-tuple -> custom_vjp fn (trace cache)

        def _build(full):
            keys = sorted(full)

            @jax.custom_vjp
            def inner(*t):
                return base(*t, **full)

            inner.defvjp(
                lambda *t: vjp_fwd(*t, **full),
                lambda res, g: tuple(
                    vjp_bwd(*(full[k] for k in keys), res, g)))
            return inner

        @functools.wraps(fn)
        def fn(*arrays, **params):  # noqa: F811 — deliberate rewrap
            full = {**fwd_defaults, **params}
            try:    # unhashable static params (lists...) skip caching
                key = tuple(sorted(full.items()))
                inner = vjp_cache.get(key)
            except TypeError:
                return _build(full)(*arrays)
            if inner is None:
                inner = vjp_cache[key] = _build(full)
            return inner(*arrays)

        if differentiable is None:
            differentiable = True
    if differentiable is None:
        differentiable = True
    # infer arg_names from the *original* callable's signature when
    # not given (compile_kernel's wrapper is (*arrays, **params))
    if arg_names is None:
        import inspect
        try:
            sig = inspect.signature(fn)
            arg_names = [p.name for p in sig.parameters.values()
                         if p.kind in (p.POSITIONAL_ONLY,
                                       p.POSITIONAL_OR_KEYWORD)
                         and p.default is p.empty
                         and not p.name.startswith("_")]
        except (TypeError, ValueError):
            arg_names = []
        if not arg_names:
            # compile_kernel wrappers expose only *arrays, so a
            # multi-input kernel registered without explicit
            # arg_names would silently become 1-ary symbolically
            # (advisor r4) — tell the user how to fix it
            import warnings
            warnings.warn(
                f"rtc.register({name!r}): cannot infer arg_names "
                "from the function signature (it takes *arrays); "
                "defaulting to ['data'] (single input).  Pass "
                "arg_names=[...] explicitly for multi-input kernels "
                "used symbolically.", stacklevel=2)
            arg_names = ["data"]
    op = OpDef(name, fn, num_outputs=num_outputs,
               arg_names=arg_names, differentiable=differentiable,
               **opdef_kwargs)
    OPS[name] = op
    ndf = _attach_frontends(name, op)
    for a in aliases:
        OPS[a] = op
        _attach_frontends(a, op)
    _RTC_ALIASES[name] = tuple(aliases)
    return ndf


def _attach_frontends(name, op):
    """Late-bind the new op onto the already-populated nd and sym
    namespaces (import-time codegen handles built-ins; custom kernels
    arrive after import)."""
    from . import ndarray as nd_mod
    from . import symbol as sym_mod
    from .ndarray.register import make_nd_func
    from .symbol.register import make_sym_func

    ndf = make_nd_func(name, op)
    symf = make_sym_func(name, op)
    for mod, f in ((nd_mod, ndf), (sym_mod, symf)):
        target = mod._internal if name.startswith("_") and \
            hasattr(mod, "_internal") else mod
        setattr(target, name, f)
    # the package-level `mx.nd` / `mx.sym` may alias these modules;
    # nothing else caches per-op lookups, so this is sufficient
    return ndf


_RTC_ALIASES = {}    # primary name -> aliases, for unregister


def unregister(name):
    """Remove a custom op registered by :func:`register` — including
    its aliases (testing / re-registration)."""
    from . import ndarray as nd_mod
    from . import symbol as sym_mod
    for n in (name,) + _RTC_ALIASES.pop(name, ()):
        OPS.pop(n, None)
        for mod in (nd_mod, sym_mod):
            target = mod._internal if n.startswith("_") and \
                hasattr(mod, "_internal") else mod
            if hasattr(target, n):
                delattr(target, n)
