"""Checkpoint helpers + BatchEndParam (ref: python/mxnet/model.py —
save_checkpoint/load_checkpoint, BatchEndParam:... , _create_kvstore:57).
"""
import collections

from . import kvstore as kvs
from . import ndarray as nd
from . import symbol as sym

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "save_data_state", "load_data_state",
           "_create_kvstore", "FeedForward"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """(ref: model.py:57) resolve kvstore spec -> (kv, update_on_kvstore)."""
    if kvstore is None:
        return None, False
    if isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore and \
                kvstore != "tpu":
            return None, False
        kv = kvs.create(kvstore)
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    update_on_kvstore = True
    if arg_params:
        max_size = max(int(nd_arr.size)
                       for nd_arr in arg_params.values())
        if max_size > 1024 * 1024 * 16:
            update_on_kvstore = False
    return kv, update_on_kvstore


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save symbol JSON + params (ref: model.py save_checkpoint).
    Format: prefix-symbol.json + prefix-NNNN.params."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)
    from . import telemetry
    telemetry.counter("checkpoint_saves_total").inc()


def split_tagged_params(save_dict):
    """Split a saved params dict on its ``arg:``/``aux:`` tags ->
    (arg_params, aux_params).  Untagged keys (a raw ``nd.save`` of a
    param dict) count as args; unknown tags are ignored."""
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if not name:
            arg_params[k] = v
        elif tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def _checkpoint_epochs(prefix):
    """(epoch, path) pairs for on-disk ``prefix-N.params`` files,
    newest epoch first.  The globbed path travels with the epoch so
    a fallback load opens the file that actually exists — not a
    ``:04d`` re-derivation that misses unpadded names.  When both a
    padded and an unpadded file claim the same epoch, the canonical
    padded one wins everywhere, so weights and their companions
    (.states) always resolve to the same file."""
    import glob
    import os
    best = {}
    for p in glob.glob(f"{glob.escape(prefix)}-*.params"):
        tail = os.path.basename(p)[len(os.path.basename(prefix)) + 1:]
        stem = tail[:-len(".params")]
        if not stem.isdigit():
            continue
        epoch = int(stem)
        if epoch not in best or p == f"{prefix}-{epoch:04d}.params":
            best[epoch] = p
    return sorted(best.items(), reverse=True)


def checkpoint_companion_path(prefix, epoch, ext=".states"):
    """Path of the per-epoch companion file (optimizer ``.states``…)
    sharing the stem of the params file that actually exists for
    ``epoch`` — resolved exactly like :func:`load_checkpoint`
    (canonical padded name first, then the on-disk scan), so the
    weights and their companion always come from the same stem."""
    import os
    want = f"{prefix}-{epoch:04d}.params"
    if not os.path.exists(want):
        for cand, path in _checkpoint_epochs(prefix):
            if cand == epoch:
                want = path
                break
    return want[:-len(".params")] + ext


def save_data_state(prefix, epoch, data_iter):
    """Checkpoint the input pipeline next to the model checkpoint:
    ``data_iter.state_dict()`` is pickled into
    ``prefix-NNNN.data`` via ``resilience.atomic_save`` (temp +
    fsync + rename + CRC32 sidecar), so a launcher restart can
    resume the stream at the exact batch instead of rewinding the
    epoch (docs/data_pipeline.md).  Returns the path written."""
    import pickle

    from . import resilience
    state = data_iter.state_dict()
    path = f"{prefix}-{epoch:04d}.data"
    resilience.atomic_save(path, lambda f: pickle.dump(state, f))
    return path


def load_data_state(prefix, epoch, data_iter, strict=False):
    """Restore ``data_iter`` from the ``.data`` companion of the
    checkpoint that actually loaded for ``epoch`` (resolved like the
    optimizer ``.states`` companion, so a corrupt-params fallback
    pairs the stream with the weights it resumed from).

    Missing or corrupt data state degrades to an epoch-start resume
    with a warning — weights are intact and rewinding one epoch of
    *data* is safe, merely wasteful — unless ``strict``.  Returns
    True when the state was applied."""
    import os
    import pickle
    import warnings

    from . import resilience
    path = checkpoint_companion_path(prefix, epoch, ext=".data")
    try:
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no data-state companion {path}")
        raw = resilience.read_validated_bytes(path)
        state = resilience.decode_or_corrupt(
            path, lambda: pickle.loads(raw))
    except (FileNotFoundError,
            resilience.CheckpointCorruptError) as exc:
        if strict:
            raise
        warnings.warn(
            f"data-pipeline state {path} could not be loaded "
            f"({exc}); resuming the stream from the epoch start",
            RuntimeWarning)
        return False
    data_iter.load_state_dict(state)
    return True


def load_checkpoint(prefix, epoch, fallback=None, return_epoch=False):
    """(ref: model.py load_checkpoint) -> (symbol, arg_params, aux_params).

    Resilience: when the requested params file is truncated/corrupt
    (CRC32 sidecar mismatch or undecodable archive — the footprint of
    a worker killed mid-save before atomic saves existed, or of disk
    bit-rot), fall back to the newest *earlier* checkpoint that
    validates, with a warning naming both epochs.  Controlled by
    ``fallback`` (default: MXTPU_CKPT_FALLBACK env flag, on).

    ``return_epoch=True`` appends the epoch that actually loaded to
    the tuple — callers pairing params with per-epoch companions
    (optimizer ``.states``, epoch counters) must use it, or a
    fallback would mix epoch-N state into epoch-M weights."""
    import os
    import warnings

    from .resilience import CheckpointCorruptError
    from .utils.env import get_env
    if fallback is None:
        fallback = get_env("MXTPU_CKPT_FALLBACK")
    symbol = None
    if os.path.exists(f"{prefix}-symbol.json"):
        symbol = sym.load(f"{prefix}-symbol.json")
    effective = epoch
    want = f"{prefix}-{epoch:04d}.params"
    if not os.path.exists(want):
        # requested epoch saved under an unpadded name — resolve it
        # through the same on-disk scan the fallback uses
        for cand, cand_path in _checkpoint_epochs(prefix):
            if cand == epoch:
                want = cand_path
                break
    try:
        save_dict = nd.load(want)
    except CheckpointCorruptError as exc:
        if not fallback:
            raise
        for cand, cand_path in _checkpoint_epochs(prefix):
            if cand >= epoch:
                continue
            try:
                save_dict = nd.load(cand_path)
            except CheckpointCorruptError:
                continue
            warnings.warn(
                f"checkpoint {prefix}-{epoch:04d}.params is corrupt "
                f"({exc}); falling back to newest valid epoch "
                f"{cand}", RuntimeWarning)
            from . import telemetry
            telemetry.counter("checkpoint_fallbacks_total").inc()
            effective = cand
            break
        else:
            raise CheckpointCorruptError(
                f"checkpoint {prefix}-{epoch:04d}.params is corrupt "
                "and no earlier checkpoint validates") from exc
    arg_params, aux_params = split_tagged_params(save_dict)
    if return_epoch:
        return symbol, arg_params, aux_params, effective
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy estimator API (ref: python/mxnet/model.py
    FeedForward:408): fit/predict/score over numpy arrays or
    DataIters, implemented as a thin shell around Module — the
    compiled-executor training path is identical; this class only
    adds the sklearn-ish ergonomics the reference's oldest examples
    use.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None,
                 optimizer="sgd", initializer=None,
                 numpy_batch_size=128, arg_params=None,
                 aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .module import Module

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.optimizer_params = kwargs
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self._mod_cls = Module
        self._module = None

    # ------------------------------------------------------------ data
    def _as_iter(self, X, y=None, shuffle=False):
        from .io.io import DataIter, NDArrayIter
        if isinstance(X, DataIter):
            return X
        import numpy as _np
        X = _np.asarray(X)
        if y is not None:
            y = _np.asarray(y, _np.float32)
        return NDArrayIter(X, y, batch_size=min(self.numpy_batch_size,
                                                len(X)),
                           shuffle=shuffle,
                           label_name="softmax_label")

    # ------------------------------------------------------------ train
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, checkpoint_prefix=None):
        """(ref: model.py FeedForward.fit:609)

        ``checkpoint_prefix`` arms the step sentinel's divergence
        rollback, exactly as in ``BaseModule.fit``."""
        import logging as _logging

        from . import initializer as init_mod

        train = self._as_iter(X, y, shuffle=True)
        if isinstance(eval_data, tuple):
            eval_data = self._as_iter(*eval_data)
        mod = self._mod_cls(self.symbol, context=self.ctx,
                            logger=logger or _logging)
        self._module = mod
        mod.fit(train, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback,
                kvstore=kvstore, optimizer=self.optimizer,
                optimizer_params=self.optimizer_params or None,
                initializer=self.initializer or init_mod.Uniform(0.01),
                arg_params=self.arg_params,
                aux_params=self.aux_params,
                allow_missing=self.arg_params is not None,
                begin_epoch=self.begin_epoch,
                checkpoint_prefix=checkpoint_prefix,
                # num_epoch is the END epoch (reference semantics):
                # a loaded model with begin_epoch=N continues for at
                # least one epoch unless told otherwise
                num_epoch=self.num_epoch if self.num_epoch is not None
                else self.begin_epoch + 1)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    # ------------------------------------------------------------ infer
    def _bound_module(self, data_iter):
        if self._module is not None and self._module.binded:
            return self._module
        assert self.arg_params is not None, "fit() or load() first"
        # loss heads (SoftmaxOutput...) keep their label argument in
        # the graph; at inference it only needs a shape, so bind a
        # dummy (batch,) desc per *_label argument
        from .io.io import DataDesc
        batch = data_iter.provide_data[0].shape[0]
        label_names = [n for n in self.symbol.list_arguments()
                       if n.endswith("_label")]
        mod = self._mod_cls(self.symbol, context=self.ctx,
                            label_names=label_names)
        mod.bind(data_shapes=data_iter.provide_data,
                 label_shapes=[DataDesc(n, (batch,))
                               for n in label_names] or None,
                 for_training=False)
        mod.set_params(self.arg_params, self.aux_params or {},
                       allow_extra=self.allow_extra_params)
        self._module = mod
        return mod

    def predict(self, X, num_batch=None):
        """Forward over X -> numpy, one array per output — a list for
        multi-output symbols (ref: FeedForward.predict:521); delegates
        to BaseModule.predict (pad-stripped, merged)."""
        import numpy as _np
        data_iter = self._as_iter(X)
        mod = self._bound_module(data_iter)
        out = mod.predict(data_iter, num_batch=num_batch)
        if isinstance(out, list):
            outs = [_np.asarray(o.asnumpy()) for o in out]
            return outs[0] if len(outs) == 1 else outs
        return _np.asarray(out.asnumpy())

    def score(self, X, y=None, eval_metric="acc", num_batch=None):
        """(ref: FeedForward.score:571); delegates to
        BaseModule.score (pad-aware)."""
        data_iter = self._as_iter(X, y)
        mod = self._bound_module(data_iter)
        return mod.score(data_iter, eval_metric,
                         num_batch=num_batch)[0][1]

    # ------------------------------------------------------------ io
    def save(self, prefix, epoch=None):
        """(ref: FeedForward.save:371)"""
        save_checkpoint(prefix, epoch if epoch is not None
                        else (self.num_epoch or 0), self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """(ref: FeedForward.load:389)

        begin_epoch is the epoch that *actually* loaded: if the
        requested params were corrupt and the resilience fallback
        substituted an earlier checkpoint, epoch numbering must
        follow the weights, not the request."""
        symbol, arg_params, aux_params, effective = load_checkpoint(
            prefix, epoch, return_epoch=True)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params,
                           begin_epoch=effective, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", **kwargs):
        """Train in one call (ref: FeedForward.create:927)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback,
                  kvstore=kvstore)
        return model
