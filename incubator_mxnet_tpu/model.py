"""Checkpoint helpers + BatchEndParam (ref: python/mxnet/model.py —
save_checkpoint/load_checkpoint, BatchEndParam:... , _create_kvstore:57).
"""
import collections

from . import kvstore as kvs
from . import ndarray as nd
from . import symbol as sym

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "_create_kvstore"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """(ref: model.py:57) resolve kvstore spec -> (kv, update_on_kvstore)."""
    if kvstore is None:
        return None, False
    if isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore and \
                kvstore != "tpu":
            return None, False
        kv = kvs.create(kvstore)
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    update_on_kvstore = True
    if arg_params:
        max_size = max(int(nd_arr.size)
                       for nd_arr in arg_params.values())
        if max_size > 1024 * 1024 * 16:
            update_on_kvstore = False
    return kv, update_on_kvstore


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save symbol JSON + params (ref: model.py save_checkpoint).
    Format: prefix-symbol.json + prefix-NNNN.params."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def split_tagged_params(save_dict):
    """Split a saved params dict on its ``arg:``/``aux:`` tags ->
    (arg_params, aux_params).  Untagged keys (a raw ``nd.save`` of a
    param dict) count as args; unknown tags are ignored."""
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if not name:
            arg_params[k] = v
        elif tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(ref: model.py load_checkpoint) -> (symbol, arg_params, aux_params)."""
    import os
    symbol = None
    if os.path.exists(f"{prefix}-symbol.json"):
        symbol = sym.load(f"{prefix}-symbol.json")
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = split_tagged_params(save_dict)
    return symbol, arg_params, aux_params
