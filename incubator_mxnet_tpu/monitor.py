"""Monitor: numeric debugging of per-op outputs (ref:
python/mxnet/monitor.py Monitor:33; executor callback ref:
src/executor/graph_executor.cc:121,1423).

The reference streams every op's outputs through a stat function via
the executor monitor callback.  Two hooks here:

* imperative dispatch (imperative_invoke) — eager NDArray code and
  non-hybridized Gluon;
* ``Executor.set_monitor_callback`` (installed by
  ``Monitor.install(executor)`` / ``Module.install_monitor``) — the
  executor's forward switches to tapped un-jitted evaluation while
  the callback is set, so every graph op's outputs reach the stat
  function.  Debugging mode: fusion is deliberately off (the
  production executable has the ops fused away).
"""
import re

import numpy as np

__all__ = ["Monitor", "nonfinite_count"]

_active_monitor = None


def _default_stat(x):
    """Mean |x| over the FINITE elements — NaN-tolerant, so one op
    emitting a few NaNs still reports a meaningful magnitude for the
    rest (all-non-finite or empty returns nan).  Pair with
    :func:`nonfinite_count` to localize which op first went bad."""
    x = np.asarray(x)
    if x.dtype.kind not in "fc":
        return float(np.abs(x).mean()) if x.size else float("nan")
    finite = np.isfinite(x)
    if not finite.any():
        return float("nan")
    return float(np.abs(x[finite]).mean())


def nonfinite_count(x):
    """Stat func counting non-finite elements per op output.

    Install as ``Monitor(stat_func=nonfinite_count)`` to localize the
    op that FIRST produced a NaN/Inf — the rows upstream of the
    poison read 0, everything downstream is contaminated.  Integer
    outputs are always 0 (finite by construction)."""
    x = np.asarray(x)
    if x.dtype.kind not in "fc":
        return 0
    return int(x.size - np.count_nonzero(np.isfinite(x)))


class Monitor:
    """Collect (batch, op_name, stat) rows while armed (ref:
    monitor.py Monitor:33 — tic/toc/toc_print)."""

    def __init__(self, interval=1, stat_func=None, pattern=".*",
                 sort=False):
        self.interval = interval
        self.stat_func = stat_func or _default_stat
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []
        self._exes = []
        self._span = None

    # ------------------------------------------------------------ install
    def install(self, target=None):
        """Arm the global dispatch hook; an Executor target
        additionally gets the per-op monitor callback (ref:
        MXExecutorSetMonitorCallback) — its forward then runs in
        tapped un-jitted mode, streaming EVERY op's outputs through
        the stat function, not just the graph heads."""
        global _active_monitor
        _active_monitor = self
        if target is not None:
            if hasattr(target, "set_monitor_callback"):
                target.set_monitor_callback(self._observe)
            if target not in self._exes:
                self._exes.append(target)
        return self

    def uninstall(self):
        global _active_monitor
        if self._span is not None:       # armed batch never toc'd
            self._span.__exit__(None, None, None)
            self._span = None
        if _active_monitor is self:
            _active_monitor = None
        for exe in self._exes:
            if hasattr(exe, "set_monitor_callback"):
                exe.set_monitor_callback(None)
        self._exes = []

    # ------------------------------------------------------------ batch
    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
            # armed batches run tapped/un-jitted — materially slower.
            # The span makes "the debug tap was on here" visible in
            # the telemetry timeline, so a perf regression that is
            # really an armed Monitor is diagnosable from the trace
            # alone (docs/observability.md).
            from . import telemetry
            if self._span is not None:
                # the prior armed batch aborted between tic and toc
                # (an exception in forward/update skipped toc): close
                # its span now so the armed section still lands in
                # the timeline instead of leaking open — it measures
                # tic-to-rearm, slightly long, but visible
                self._span.__exit__(None, None, None)
            self._span = telemetry.span("monitor_armed")
            self._span.__enter__()
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        if self._span is not None:
            self._span.__exit__(None, None, None)
            self._span = None
        for exe in self._exes:
            if getattr(exe, "_monitor_cb", None) is not None:
                continue    # tapped: per-op rows already streamed
            outputs = getattr(exe, "outputs", None) or []
            names = []
            sym = getattr(exe, "_symbol", None)
            if sym is not None:
                names = sym.list_outputs()
            for i, o in enumerate(outputs):
                name = names[i] if i < len(names) else f"output{i}"
                if self.pattern.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(o.asnumpy())))
        res = self.queue
        self.queue = []
        if self.sort:
            res = sorted(res, key=lambda r: r[1])
        from . import telemetry
        telemetry.counter("monitor_armed_batches_total").inc()
        telemetry.counter("monitor_stat_rows_total").inc(len(res))
        return res

    def toc_print(self):
        for step, name, stat in self.toc():
            print(f"Batch: {step:7d} {name:30s} {stat}")

    # ------------------------------------------------------------ hook
    def _observe(self, name, out_arrays):
        if not self.activated or not self.pattern.match(name):
            return
        for i, arr in enumerate(out_arrays):
            label = name if len(out_arrays) == 1 else f"{name}_out{i}"
            try:
                self.queue.append((self.step, label,
                                   self.stat_func(arr.asnumpy())))
            except Exception:
                pass  # non-numeric outputs


def observe_op(name, out_arrays):
    """Dispatch-path hook (called from imperative_invoke)."""
    if _active_monitor is not None:
        _active_monitor._observe(name, out_arrays)


def active():
    return _active_monitor is not None and _active_monitor.activated
