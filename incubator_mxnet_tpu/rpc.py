"""Shared framed RPC transport (the ps-lite van role).

One frame = a fixed struct header + a JSON payload:

    !4sIId  ->  magic  b"MXRF"
                payload length (bytes)
                CRC32 of the payload
                remaining deadline budget (s, float64; 0 = none)

The CRC makes corruption *detectable* (a garbled frame raises
:class:`RpcFrameError` and the receiver drops the connection — once
framing is suspect the whole stream is) and the header's float64
propagates the *remaining* per-request deadline across the process
boundary, so a request re-dispatched after a replica death runs under
what is left of its budget, not a fresh one (docs/serving.md "Fleet").

Two planes speak this transport: the serving fleet
(``serving/rpc.py`` re-exports everything here unchanged) and the
remote data-service ranks (``data_service/net.py``,
docs/data_service.md "Remote ranks").  The only plane-specific knob
is the fault-injection scope: the frame *send* path consults
``fault_scope`` (default ``("router", "net")``; the data plane's
batch stream passes ``("data_service", "net")``, control frames pass
``None`` to skip injection) — ``corrupt`` garbles one payload byte
after the CRC is computed (the receiver rejects the frame), ``error``
drops the frame and closes the connection, ``hang`` delays it by
MXTPU_FAULT_HANG_S (the caller's deadline decides the outcome).

Every socket wait is bounded: each operation computes the remaining
per-call budget (``MXTPU_RPC_TIMEOUT`` by default) and arms
``settimeout`` before touching the socket — ci/lint.py rejects bare
``recv``/``accept``/``connect`` in this module without an explicit
``deadline-ok`` annotation.  Timeouts raise :class:`RpcTimeoutError`
(a :class:`~.resilience.DeadlineExceededError`), transport failures
:class:`RpcError`; reconnects back off with full jitter
(``RetryPolicy(jitter=True)``) so N replicas re-homing after a router
blip do not retry in lockstep.
"""
import json
import select
import socket
import struct
import threading
import time
import zlib

from . import resilience, telemetry
from .utils.env import get_env
from .utils.log import get_logger

logger = get_logger("rpc")

MAGIC = b"MXRF"
_HEADER = struct.Struct("!4sIId")
#: refuse absurd frame lengths before allocating (a corrupted length
#: field must not look like an OOM)
MAX_FRAME_BYTES = 64 << 20

#: default injection point for the frame send path (the serving
#: fleet's scope); pass fault_scope=None to bypass injection
DEFAULT_FAULT_SCOPE = ("router", "net")
_SCOPE_UNSET = object()

_m_frame_errors = telemetry.counter("rpc_frame_errors_total")
_m_frames_sent = telemetry.counter("rpc_frames_sent_total")
_m_reconnects = telemetry.counter("rpc_reconnects_total")


class RpcError(resilience.ResilienceError):
    """Transport-level RPC failure (peer gone, send/recv failed)."""


class RpcTimeoutError(RpcError, resilience.DeadlineExceededError):
    """An RPC socket wait exceeded its per-call deadline."""


class RpcFrameError(RpcError):
    """A received frame failed validation (magic, length, CRC,
    payload decode).  The connection is considered poisoned — framing
    can no longer be trusted — so receivers close it and let the peer
    reconnect."""


def default_timeout():
    """The mandatory per-call deadline (s).  ``MXTPU_RPC_TIMEOUT``;
    non-positive values are coerced to 30 s — this layer never waits
    unbounded."""
    t = get_env("MXTPU_RPC_TIMEOUT")
    return t if t > 0 else 30.0


def _deadline(timeout):
    """Monotonic deadline stamp for one call."""
    return time.monotonic() + (default_timeout()
                               if timeout is None else timeout)


def _remaining(deadline, what):
    rem = deadline - time.monotonic()
    if rem <= 0:
        raise RpcTimeoutError(f"rpc deadline exceeded during {what}")
    return rem


def encode_frame(msg, budget=0.0):
    """Serialize one message dict to wire bytes (header + JSON)."""
    payload = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise RpcFrameError(
            f"frame payload {len(payload)}B exceeds "
            f"{MAX_FRAME_BYTES}B")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    header = _HEADER.pack(MAGIC, len(payload), crc, float(budget))
    return header, payload


def send_frame(sock, msg, budget=0.0, timeout=None, lock=None,
               fault_scope=DEFAULT_FAULT_SCOPE):
    """Send one frame with a bounded deadline.

    ``budget`` is the remaining per-request deadline to propagate in
    the header (0 = none).  ``lock`` (if given) serializes writers on
    a shared socket.  ``fault_scope`` names the ``MXTPU_FAULT_SPEC``
    injection point consulted here (``("router", "net")`` for the
    serving fleet, ``("data_service", "net")`` for the data plane's
    batch stream, ``None`` to bypass): the CRC is computed over the
    *clean* payload first, so an injected ``corrupt`` flips a byte
    the receiver's CRC check catches.
    """
    deadline = _deadline(timeout)
    header, payload = encode_frame(msg, budget)
    kind = resilience.fault_for(*fault_scope) \
        if fault_scope is not None else None
    if kind == "corrupt":
        # garble one payload byte AFTER the CRC was computed: the
        # receiver must reject the frame and drop the connection
        payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
    elif kind == "error":
        # drop the frame on the floor and poison the link, like a
        # mid-write connection reset
        try:
            sock.close()
        except OSError:
            pass
        raise RpcError(
            "injected frame drop for %s:%s" % fault_scope)
    elif kind == "hang":
        # a delayed frame: the caller's deadline, not this sleep,
        # decides the request's fate
        time.sleep(get_env("MXTPU_FAULT_HANG_S"))
    data = header + payload
    lock = lock if lock is not None else threading.Lock()
    with lock:
        try:
            sock.settimeout(_remaining(deadline, "send"))
            sock.sendall(data)
        except (socket.timeout, TimeoutError):
            raise RpcTimeoutError(
                "rpc deadline exceeded during send") from None
        except OSError as e:
            raise RpcError(f"rpc send failed: {e}") from None
    _m_frames_sent.inc()


def _recv_exact(sock, n, deadline, what):
    buf = bytearray()
    while len(buf) < n:
        try:
            sock.settimeout(_remaining(deadline, what))
            # deadline-ok: settimeout armed above from the deadline
            chunk = sock.recv(n - len(buf))
        except (socket.timeout, TimeoutError):
            if buf:
                # a MID-FRAME timeout already consumed bytes the
                # next read can never re-frame: the stream is
                # desynchronized, not merely idle — poison it
                raise RpcError(
                    f"rpc stream desynchronized: timeout mid-"
                    f"{what} after {len(buf)}/{n} bytes") from None
            raise RpcTimeoutError(
                f"rpc deadline exceeded during {what}") from None
        except OSError as e:
            raise RpcError(f"rpc recv failed: {e}") from None
        if not chunk:
            raise RpcError("connection closed by peer")
        buf += chunk
    return bytes(buf)


def recv_frame(sock, timeout=None):
    """Receive one frame; returns ``(msg, budget)``.

    ``timeout`` bounds the wait for the frame to *start* (reader
    loops poll with a short one — :class:`RpcTimeoutError` then just
    means "idle tick", and crucially consumes nothing).  Once the
    first byte is in flight the frame gets the full default deadline
    to complete; a timeout mid-frame has consumed bytes the stream
    cannot re-frame, so it poisons the connection (:class:`RpcError`)
    instead of pretending the link is idle.

    Raises :class:`RpcFrameError` on any validation failure —
    callers must treat the connection as poisoned afterwards.
    """
    wait = default_timeout() if timeout is None else timeout
    try:
        # deadline-ok: select bounded by the poll/call timeout;
        # consumes nothing, so a timeout here leaves framing intact
        ready, _, _ = select.select([sock], [], [], max(wait, 0.0))
    except (OSError, ValueError) as e:
        raise RpcError(f"rpc recv failed: {e}") from None
    if not ready:
        raise RpcTimeoutError(
            "rpc deadline exceeded waiting for a frame")
    deadline = _deadline(None)
    raw = _recv_exact(sock, _HEADER.size, deadline, "recv header")
    magic, length, crc, budget = _HEADER.unpack(raw)
    if magic != MAGIC:
        _m_frame_errors.inc()
        raise RpcFrameError(f"bad frame magic {magic!r}")
    if length > MAX_FRAME_BYTES:
        _m_frame_errors.inc()
        raise RpcFrameError(f"frame length {length}B exceeds "
                            f"{MAX_FRAME_BYTES}B")
    payload = _recv_exact(sock, length, deadline, "recv payload")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        _m_frame_errors.inc()
        raise RpcFrameError("frame CRC mismatch (corrupted payload)")
    try:
        msg = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        _m_frame_errors.inc()
        raise RpcFrameError(f"frame payload decode failed: {e}") \
            from None
    return msg, budget


class RpcClient:
    """One outbound connection speaking the frame protocol.

    Thread contract: any number of threads may :meth:`send` (writes
    are lock-serialized); at most ONE thread may :meth:`recv` (the
    link's reader).  :meth:`call` (send + one reply) is only safe
    when no concurrent reader owns the socket.
    """

    def __init__(self, host, port, timeout=None,
                 fault_scope=DEFAULT_FAULT_SCOPE):
        self.host = host
        self.port = int(port)
        self.timeout = (default_timeout()
                        if timeout is None else float(timeout))
        self.fault_scope = fault_scope
        self._sock = None
        self._send_lock = threading.Lock()

    @property
    def connected(self):
        return self._sock is not None

    def connect(self, timeout=None):
        """One bounded connection attempt (no retries)."""
        self.close()
        rem = self.timeout if timeout is None else timeout
        try:
            # deadline-ok: create_connection bounded by timeout arg
            sock = socket.create_connection(
                (self.host, self.port), timeout=rem)
        except (socket.timeout, TimeoutError):
            raise RpcTimeoutError(
                f"rpc connect to {self.host}:{self.port} timed "
                "out") from None
        except OSError as e:
            raise RpcError(
                f"rpc connect to {self.host}:{self.port} failed: "
                f"{e}") from None
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        return self

    def connect_retry(self, policy=None):
        """Connect with full-jitter backoff: the reconnect path N
        replicas/links share after a blip, so deterministic backoff
        would retry in lockstep (thundering herd)."""
        if policy is None:
            policy = resilience.RetryPolicy(jitter=True)
        _m_reconnects.inc()
        resilience.retry_call(
            self.connect, policy=policy, retry_on=(RpcError,),
            op_name=f"rpc_connect:{self.host}:{self.port}")
        return self

    def send(self, msg, budget=0.0, timeout=None,
             fault_scope=_SCOPE_UNSET):
        if self._sock is None:
            raise RpcError("rpc client not connected")
        try:
            send_frame(self._sock, msg, budget=budget,
                       timeout=self.timeout if timeout is None
                       else timeout,
                       lock=self._send_lock,
                       fault_scope=self.fault_scope
                       if fault_scope is _SCOPE_UNSET
                       else fault_scope)
        except RpcError:
            self.close()
            raise

    def recv(self, timeout=None):
        if self._sock is None:
            raise RpcError("rpc client not connected")
        try:
            return recv_frame(self._sock,
                              timeout=self.timeout if timeout is None
                              else timeout)
        except RpcTimeoutError:
            raise            # socket still healthy: caller may poll again
        except RpcError:
            self.close()
            raise

    def call(self, msg, budget=0.0, timeout=None):
        """Send one frame and wait for one reply frame (single
        caller only — see the thread contract)."""
        t = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + t
        self.send(msg, budget=budget, timeout=t)
        return self.recv(timeout=_remaining(deadline, "call reply"))

    def close(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def call_once(host, port, msg, timeout=None):
    """Connect, send one frame, wait for one reply, close — under a
    single monotonic deadline covering all three phases.  The
    one-shot shape debugz/status pollers need: a SIGSTOPped peer
    costs at most ``timeout`` seconds, never a wedged caller."""
    t = default_timeout() if timeout is None else float(timeout)
    deadline = time.monotonic() + t
    cli = RpcClient(host, port, timeout=t, fault_scope=None)
    try:
        cli.connect(timeout=_remaining(deadline, "call_once connect"))
        return cli.call(msg, timeout=_remaining(deadline,
                                                "call_once reply"))
    finally:
        cli.close()


class _Conn:
    """Server-side handle for one accepted connection."""

    def __init__(self, sock, peer, fault_scope=DEFAULT_FAULT_SCOPE):
        self.sock = sock
        self.peer = peer
        self.fault_scope = fault_scope
        self._send_lock = threading.Lock()
        self._closed = False

    def send(self, msg, budget=0.0, timeout=None,
             fault_scope=_SCOPE_UNSET):
        if self._closed:
            raise RpcError(f"connection to {self.peer} closed")
        try:
            send_frame(self.sock, msg, budget=budget,
                       timeout=timeout, lock=self._send_lock,
                       fault_scope=self.fault_scope
                       if fault_scope is _SCOPE_UNSET
                       else fault_scope)
        except RpcError:
            self.close()
            raise

    def close(self):
        self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass

    @property
    def closed(self):
        return self._closed


class RpcServer:
    """Threaded frame server.

    ``handler(msg, conn, budget)`` runs on the per-connection reader
    thread; a non-None return value is sent back on the same
    connection.  A frame that fails validation poisons its
    connection: the server closes it (and counts
    ``rpc_frame_errors_total``) and the peer reconnects — subsequent
    requests are not poisoned because state lives above the
    transport.  ``fault_scope`` is the default injection point for
    replies sent on this server's connections (see
    :func:`send_frame`).
    """

    def __init__(self, handler, host="127.0.0.1", port=0,
                 name="rpc", poll=0.2, on_disconnect=None,
                 fault_scope=DEFAULT_FAULT_SCOPE):
        self._handler = handler
        self._name = name
        self._poll = poll
        self._on_disconnect = on_disconnect
        self._fault_scope = fault_scope
        self._stop = threading.Event()
        self._conns = []
        self._threads = []
        self._lock = threading.Lock()
        self._lsock = socket.socket(socket.AF_INET,
                                    socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET,
                               socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(16)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._accept_thread = None

    def start(self):
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"{self._name}-accept", daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        self._lsock.settimeout(self._poll)
        while not self._stop.is_set():
            try:
                # deadline-ok: settimeout(poll) above bounds accept
                sock, addr = self._lsock.accept()
            except (socket.timeout, TimeoutError):
                continue
            except OSError:
                break
            sock.setsockopt(socket.IPPROTO_TCP,
                            socket.TCP_NODELAY, 1)
            conn = _Conn(sock, f"{addr[0]}:{addr[1]}",
                         fault_scope=self._fault_scope)
            t = threading.Thread(
                target=self._reader_loop, args=(conn,),
                name=f"{self._name}-conn", daemon=True)
            with self._lock:
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _reader_loop(self, conn):
        while not self._stop.is_set() and not conn.closed:
            try:
                msg, budget = recv_frame(conn.sock,
                                         timeout=self._poll)
            except RpcTimeoutError:
                continue             # idle poll tick, link healthy
            except RpcFrameError as e:
                logger.warning("%s: dropping poisoned connection "
                               "from %s: %s", self._name, conn.peer,
                               e)
                conn.close()
                break
            except (RpcError, OSError):
                conn.close()
                break
            try:
                reply = self._handler(msg, conn, budget)
            except Exception as e:     # noqa: BLE001 — handler bugs must not kill the reader
                logger.exception("%s: handler failed for op=%r",
                                 self._name, msg.get("op"))
                try:
                    conn.send({"op": "error", "error": str(e)})
                except RpcError:
                    break
                continue
            if reply is not None:
                try:
                    conn.send(reply)
                except RpcError:
                    break
        if self._on_disconnect is not None:
            try:
                self._on_disconnect(conn)
            except Exception:          # noqa: BLE001 — teardown callback must not raise
                logger.exception("%s: on_disconnect failed",
                                 self._name)

    def connections(self):
        with self._lock:
            return [c for c in self._conns if not c.closed]

    def close(self):
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            threads = list(self._threads)
        for c in conns:
            c.close()
        for t in threads:
            t.join(timeout=2.0)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
