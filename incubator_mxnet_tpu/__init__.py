"""incubator_mxnet_tpu — a TPU-native deep learning framework with the
capability surface of Apache MXNet 0.12.1 (reference:
solin319/incubator-mxnet), re-designed for JAX/XLA/Pallas/pjit.

Usage mirrors the reference::

    import incubator_mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))

Layering (cf. SURVEY.md §1): context/engine facades over PJRT+XLA
async dispatch -> NDArray -> central op registry (generates nd & sym
surfaces) -> autograd tape / Symbol graph -> Executor (whole graph =
one XLA executable) -> Module & Gluon trainers -> KVStore over
ICI-mesh collectives.
"""
from .base import __version__, TShape, MXTPUError
from . import utils
from .context import (Context, cpu, tpu, gpu, cpu_pinned, num_tpus,
                      num_gpus, current_context, default_context,
                      tpu_memory_info, gpu_memory_info)
from . import engine
from . import ops
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import random_state
from . import random
from . import autograd
from . import name
from . import attribute
from .attribute import AttrScope
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from .executor import Executor
from . import graph
from . import io
from . import initializer
from .initializer import init
from . import optimizer
from .optimizer import Optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import kvstore
from . import kvstore as kv
from . import model
from . import test_utils
from . import dist
from . import resilience
from . import telemetry
from . import tracing
from . import predictor
from .predictor import Predictor
from .model import load_checkpoint, save_checkpoint
from . import module
from . import module as mod
from .module import Module
from .io import DataBatch, DataDesc, DataIter, NDArrayIter
from . import gluon
from . import serving
from . import rnn
from . import recordio
from . import image
from . import operator
from . import rtc
from . import profiler
from . import monitor
from .monitor import Monitor
from . import visualization
from . import parallel
from . import contrib
from .utils.env import list_env

__all__ = ["nd", "ndarray", "autograd", "Context", "cpu", "tpu", "gpu",
           "random", "NDArray", "TShape", "sym", "symbol", "Symbol",
           "Executor", "io", "initializer", "init", "optimizer",
           "lr_scheduler", "metric", "callback", "kvstore", "model",
           "module", "mod", "Module", "gluon", "DataBatch", "DataDesc",
           "DataIter", "NDArrayIter", "load_checkpoint",
           "save_checkpoint", "list_env", "resilience", "telemetry",
           "serving", "__version__"]
