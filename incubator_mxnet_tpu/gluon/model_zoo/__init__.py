"""Gluon model zoo (ref: python/mxnet/gluon/model_zoo/)."""
from . import vision
from . import model_store
from .vision import get_model

__all__ = ["vision", "get_model", "model_store"]
