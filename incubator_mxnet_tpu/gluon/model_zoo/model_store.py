"""Pretrained-weight store (ref role:
python/mxnet/gluon/model_zoo/model_store.py — get_model_file/purge).

The reference resolves ``pretrained=True`` by downloading
``<name>-<sha1[:8]>.params`` from its S3 bucket into
``~/.mxnet/models`` and sha1-checking it.  This environment has zero
egress, so the store is purely local: weights are *installed* into
the cache (``import_model_file`` — e.g. converted from another
framework offline, or trained here and published), and
``get_model_file`` resolves from it.  The cache root is
``$MXTPU_HOME/models`` (default ``~/.mxtpu/models``), overridable per
call exactly like the reference's ``root=`` argument.
"""
import hashlib
import os
import shutil

__all__ = ["get_model_file", "import_model_file", "purge",
           "list_models"]


def _default_root():
    home = os.environ.get("MXTPU_HOME",
                          os.path.join(os.path.expanduser("~"),
                                       ".mxtpu"))
    return os.path.join(home, "models")


def _file_name(name, sha1=None):
    return f"{name}-{sha1[:8]}.params" if sha1 else f"{name}.params"


def get_model_file(name, root=None):
    """Path of the cached params file for ``name``.

    Accepts both the plain ``<name>.params`` layout and the
    reference's sha1-tagged ``<name>-xxxxxxxx.params`` (in which case
    the newest tagged file wins and its digest is verified).
    Raises FileNotFoundError with install instructions if absent —
    the download the reference would attempt cannot happen here.
    """
    root = os.path.expanduser(root or _default_root())
    plain = os.path.join(root, _file_name(name))
    if os.path.exists(plain):
        return plain
    if os.path.isdir(root):
        tagged = sorted(
            (f for f in os.listdir(root)
             if f.startswith(name + "-") and f.endswith(".params")
             and len(f) == len(name) + 1 + 8 + len(".params")),
            key=lambda f: os.path.getmtime(os.path.join(root, f)))
        if tagged:
            path = os.path.join(root, tagged[-1])
            tag = tagged[-1][len(name) + 1:-len(".params")]
            if not _sha1(path).startswith(tag):
                raise OSError(
                    f"checksum mismatch for {path}; re-install it "
                    f"(import_model_file) or delete it (purge)")
            return path
    raise FileNotFoundError(
        f"no pretrained weights for '{name}' in {root} (zero-egress "
        f"environment: the reference would download them; here "
        f"install a params file with "
        f"model_store.import_model_file(src, '{name}') or save one "
        f"to {plain})")


def import_model_file(src, name, root=None):
    """Install a params file into the cache under ``name`` with the
    reference's sha1-tagged file name; returns the cached path."""
    root = os.path.expanduser(root or _default_root())
    os.makedirs(root, exist_ok=True)
    dst = os.path.join(root, _file_name(name, _sha1(src)))
    shutil.copyfile(src, dst)
    return dst


def list_models(root=None):
    """Names with weights available in the cache."""
    root = os.path.expanduser(root or _default_root())
    if not os.path.isdir(root):
        return []
    names = set()
    for f in os.listdir(root):
        if f.endswith(".params"):
            stem = f[:-len(".params")]
            base, dash, tag = stem.rpartition("-")
            names.add(base if dash and len(tag) == 8 else stem)
    return sorted(names)


def purge(root=None):
    """Delete every cached params file (ref: model_store.purge)."""
    root = os.path.expanduser(root or _default_root())
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))


def load_pretrained(net, name, ctx=None, root=None):
    """Resolve ``name`` in the store and load it into ``net`` — the
    factory-side half of the reference's ``pretrained=True`` flow."""
    net.load_params(get_model_file(name, root=root), ctx=ctx)
    return net


def _sha1(path):
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()
