"""VGG 11/13/16/19 (+BN) (ref: python/mxnet/gluon/model_zoo/vision/
vgg.py)."""
from ... import nn
from ...block import HybridBlock

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn",
           "vgg13_bn", "vgg16_bn", "vgg19_bn", "get_vgg"]

vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for i, num in enumerate(layers):
                for _ in range(num):
                    self.features.add(nn.Conv2D(filters[i], 3,
                                                padding=1))
                    if batch_norm:
                        self.features.add(nn.BatchNorm())
                    self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(2, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_vgg(num_layers, pretrained=False, ctx=None, root=None,
            **kwargs):
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        bn = "_bn" if kwargs.get("batch_norm") else ""
        load_pretrained(net, f"vgg{num_layers}{bn}", ctx=ctx,
                        root=root)
    return net


def _make(n, bn):
    def f(**kwargs):
        if bn:
            kwargs["batch_norm"] = True
        return get_vgg(n, **kwargs)
    f.__name__ = f"vgg{n}" + ("_bn" if bn else "")
    return f


vgg11, vgg13, vgg16, vgg19 = (_make(n, False) for n in (11, 13, 16, 19))
vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn = (_make(n, True)
                                          for n in (11, 13, 16, 19))
