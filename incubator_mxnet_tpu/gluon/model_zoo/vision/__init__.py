"""Model zoo vision models (ref: python/mxnet/gluon/model_zoo/vision/).
"""
from .resnet import *    # noqa: F401,F403
from .alexnet import *   # noqa: F401,F403
from .vgg import *       # noqa: F401,F403
from .others import *    # noqa: F401,F403

from .resnet import __all__ as _r
from .alexnet import __all__ as _a
from .vgg import __all__ as _v
from .others import __all__ as _o

__all__ = list(_r) + list(_a) + list(_v) + list(_o) + ["get_model"]

_models = {}


def _collect():
    import sys
    mod = sys.modules[__name__]
    for name in __all__:
        f = getattr(mod, name, None)
        if callable(f) and name[0].islower():
            _models[name] = f


_collect()


def get_model(name, **kwargs):
    """Get a model by name (ref: model_zoo/__init__.py get_model)."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"unknown model '{name}'; available: {sorted(_models)}")
    return _models[name](**kwargs)
