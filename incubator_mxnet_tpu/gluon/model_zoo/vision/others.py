"""SqueezeNet, DenseNet, MobileNet, Inception-v3 (ref:
python/mxnet/gluon/model_zoo/vision/{squeezenet,densenet,mobilenet,
inception}.py)."""
from ... import nn
from ...block import HybridBlock

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1", "DenseNet",
           "densenet121", "densenet161", "densenet169", "densenet201",
           "MobileNet", "mobilenet1_0", "mobilenet0_75", "mobilenet0_5",
           "mobilenet0_25", "Inception3", "inception_v3"]


def _pretrained(net, pretrained, name, ctx=None, root=None):
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, name, ctx=ctx, root=root)
    return net


# ---------------------------------------------------------------- squeeze
class _Fire(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze, 1, activation="relu")
        self.expand1 = nn.Conv2D(expand1x1, 1, activation="relu")
        self.expand3 = nn.Conv2D(expand3x3, 3, padding=1,
                                 activation="relu")

    def shape_from_input(self, *i):
        pass

    def hybrid_forward(self, F, x):
        x = self.squeeze(x)
        return F.Concat(self.expand1(x), self.expand3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version="1.0", classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(nn.Conv2D(96, 7, 2,
                                            activation="relu"))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(16, 64), (16, 64), (32, 128)]:
                    self.features.add(_Fire(s, e, e))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(32, 128), (48, 192), (48, 192),
                             (64, 256)]:
                    self.features.add(_Fire(s, e, e))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                self.features.add(_Fire(64, 256, 256))
            else:
                self.features.add(nn.Conv2D(64, 3, 2,
                                            activation="relu"))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(16, 64), (16, 64)]:
                    self.features.add(_Fire(s, e, e))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(32, 128), (32, 128)]:
                    self.features.add(_Fire(s, e, e))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(48, 192), (48, 192), (64, 256),
                             (64, 256)]:
                    self.features.add(_Fire(s, e, e))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, 1, activation="relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def shape_from_input(self, *i):
        pass

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(pretrained=False, ctx=None, root=None, **kw):
    return _pretrained(SqueezeNet("1.0", **kw), pretrained,
                       "squeezenet1.0", ctx, root)


def squeezenet1_1(pretrained=False, ctx=None, root=None, **kw):
    return _pretrained(SqueezeNet("1.1", **kw), pretrained,
                       "squeezenet1.1", ctx, root)


# ---------------------------------------------------------------- dense
class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, 1,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, 3, padding=1,
                                use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def shape_from_input(self, *i):
        pass

    def hybrid_forward(self, F, x):
        return F.Concat(x, self.body(x), dim=1)


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
            num_features = num_init_features
            for i, num_layers in enumerate(block_config):
                for _ in range(num_layers):
                    self.features.add(_DenseLayer(growth_rate, bn_size,
                                                  dropout))
                num_features += num_layers * growth_rate
                if i != len(block_config) - 1:
                    self.features.add(nn.BatchNorm())
                    self.features.add(nn.Activation("relu"))
                    self.features.add(nn.Conv2D(num_features // 2, 1,
                                                use_bias=False))
                    self.features.add(nn.AvgPool2D(2, 2))
                    num_features //= 2
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def shape_from_input(self, *i):
        pass

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


densenet_spec = {121: (64, 32, [6, 12, 24, 16]),
                 161: (96, 48, [6, 12, 36, 24]),
                 169: (64, 32, [6, 12, 32, 32]),
                 201: (64, 32, [6, 12, 48, 32])}


def _make_dense(n):
    def f(pretrained=False, ctx=None, root=None, **kw):
        a, b, c = densenet_spec[n]
        return _pretrained(DenseNet(a, b, c, **kw), pretrained,
                           f"densenet{n}", ctx, root)
    f.__name__ = f"densenet{n}"
    return f


densenet121 = _make_dense(121)
densenet161 = _make_dense(161)
densenet169 = _make_dense(169)
densenet201 = _make_dense(201)


# ---------------------------------------------------------------- mobile
class MobileNet(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        dw_channels = [int(x * multiplier) for x in
                       [32, 64] + [128] * 2 + [256] * 2 + [512] * 6
                       + [1024]]
        channels = [int(x * multiplier) for x in
                    [64] + [128] * 2 + [256] * 2 + [512] * 6
                    + [1024] * 2]
        strides = [1, 2] * 3 + [1] * 5 + [2, 1]
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(int(32 * multiplier), 3, 2, 1,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            for dwc, c, s in zip(dw_channels, channels, strides):
                # depthwise
                self.features.add(nn.Conv2D(dwc, 3, s, 1, groups=dwc,
                                            use_bias=False,
                                            in_channels=dwc))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                # pointwise
                self.features.add(nn.Conv2D(c, 1, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def shape_from_input(self, *i):
        pass

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _make_mobile(mult, suffix):
    def f(pretrained=False, ctx=None, root=None, **kw):
        return _pretrained(MobileNet(mult, **kw), pretrained,
                           f"mobilenet{suffix}", ctx, root)
    f.__name__ = f"mobilenet{suffix}"
    return f


mobilenet1_0 = _make_mobile(1.0, "1_0")
mobilenet0_75 = _make_mobile(0.75, "0_75")
mobilenet0_5 = _make_mobile(0.5, "0_5")
mobilenet0_25 = _make_mobile(0.25, "0_25")


# ---------------------------------------------------------------- incep
def _conv_bn(channels, kernel, stride=1, pad=0):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel, stride, pad, use_bias=False))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Concurrent(HybridBlock):
    """Parallel branches concatenated on channel axis."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._branches = []

    def add(self, block):
        self._branches.append(block)
        self.register_child(block)

    def shape_from_input(self, *i):
        pass

    def hybrid_forward(self, F, x):
        outs = [b(x) for b in self._branches]
        return F.Concat(*outs, dim=1)


def _make_A(pool_features, prefix):
    out = _Concurrent(prefix=prefix)
    out.add(_conv_bn(64, 1))
    b2 = nn.HybridSequential(prefix="")
    b2.add(_conv_bn(48, 1))
    b2.add(_conv_bn(64, 5, pad=2))
    out.add(b2)
    b3 = nn.HybridSequential(prefix="")
    b3.add(_conv_bn(64, 1))
    b3.add(_conv_bn(96, 3, pad=1))
    b3.add(_conv_bn(96, 3, pad=1))
    out.add(b3)
    b4 = nn.HybridSequential(prefix="")
    b4.add(nn.AvgPool2D(3, 1, 1))
    b4.add(_conv_bn(pool_features, 1))
    out.add(b4)
    return out


class Inception3(HybridBlock):
    """Inception v3 (299x299) — abbreviated faithful topology."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            f = nn.HybridSequential(prefix="")
            f.add(_conv_bn(32, 3, 2))
            f.add(_conv_bn(32, 3))
            f.add(_conv_bn(64, 3, pad=1))
            f.add(nn.MaxPool2D(3, 2))
            f.add(_conv_bn(80, 1))
            f.add(_conv_bn(192, 3))
            f.add(nn.MaxPool2D(3, 2))
            f.add(_make_A(32, "A1_"))
            f.add(_make_A(64, "A2_"))
            f.add(_make_A(64, "A3_"))
            # reduction
            red = _Concurrent(prefix="B_")
            red.add(_conv_bn(384, 3, 2))
            b = nn.HybridSequential(prefix="")
            b.add(_conv_bn(64, 1))
            b.add(_conv_bn(96, 3, pad=1))
            b.add(_conv_bn(96, 3, 2))
            red.add(b)
            bp = nn.HybridSequential(prefix="")
            bp.add(nn.MaxPool2D(3, 2))
            red.add(bp)
            f.add(red)
            for _ in range(2):
                f.add(_make_A(192, None))
            f.add(nn.GlobalAvgPool2D())
            f.add(nn.Dropout(0.5))
            f.add(nn.Flatten())
            self.features = f
            self.output = nn.Dense(classes)

    def shape_from_input(self, *i):
        pass

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, ctx=None, root=None, **kw):
    return _pretrained(Inception3(**kw), pretrained, "inception_v3",
                       ctx, root)
