"""Decoder-only transformer LM built from framework layers/ops.

A model family the reference era predates but today's users expect;
built TPU-first: every matmul (qkv/proj/mlp/head and the two
batch_dot attention products) lands on the MXU, shapes are static
under jit, and the causal mask is an additive constant folded by XLA.
Trains through the same paths as every other Block (Trainer,
ShardedTrainStep's kvstore='tpu' mesh step, bf16 master-weight mode);
for sequence-parallel scale-out the attention core swaps for
parallel.ring_attention (see parallel/ring_attention.py).
"""
import math

import numpy as np

from ... import ndarray as nd
from ..block import Block
from ..nn import Dense, Dropout, Embedding, LayerNorm

__all__ = ["TransformerLM", "TransformerBlock", "CausalSelfAttention",
           "transformer_lm"]


class CausalSelfAttention(Block):
    """Multi-head causal self-attention over registry ops."""

    def __init__(self, d_model, n_heads, **kwargs):
        super().__init__(**kwargs)
        assert d_model % n_heads == 0
        self._d = d_model
        self._h = n_heads
        self._dh = d_model // n_heads
        with self.name_scope():
            self.qkv = Dense(3 * d_model, flatten=False, use_bias=True)
            self.proj = Dense(d_model, flatten=False, use_bias=True)

    def forward(self, x):
        b, l, d = x.shape
        h, dh = self._h, self._dh
        qkv = self.qkv(x)                          # (B, L, 3D)
        q, k, v = nd.split(qkv, num_outputs=3, axis=2)

        def heads(t):                              # (B, L, D)->(B*H, L, Dh)
            return t.reshape(b, l, h, dh).transpose(
                (0, 2, 1, 3)).reshape(b * h, l, dh)

        q, k, v = heads(q), heads(k), heads(v)
        scores = nd.batch_dot(q, k, transpose_b=True) / math.sqrt(dh)
        mask = nd.array(np.triu(
            np.full((l, l), -1e9, np.float32), k=1))
        scores = nd.broadcast_add(scores, mask.expand_dims(0))
        att = nd.softmax(scores, axis=-1)
        out = nd.batch_dot(att, v)                 # (B*H, L, Dh)
        out = out.reshape(b, h, l, dh).transpose(
            (0, 2, 1, 3)).reshape(b, l, d)
        return self.proj(out)


class TransformerBlock(Block):
    """Pre-norm attention + MLP with residuals (GPT-2 layout)."""

    def __init__(self, d_model, n_heads, mlp_ratio=4, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = LayerNorm()
            self.attn = CausalSelfAttention(d_model, n_heads)
            self.ln2 = LayerNorm()
            self.up = Dense(mlp_ratio * d_model, flatten=False,
                            activation="relu")
            self.down = Dense(d_model, flatten=False)
            self.drop = Dropout(dropout)

    def forward(self, x):
        x = x + self.drop(self.attn(self.ln1(x)))
        return x + self.drop(self.down(self.up(self.ln2(x))))


class TransformerLM(Block):
    """Token-in, logits-out decoder LM.

    Parameters: vocab_size, d_model, n_layers, n_heads, max_len
    (learned positions), mlp_ratio, dropout.
    """

    def __init__(self, vocab_size, d_model=512, n_layers=6,
                 n_heads=8, max_len=1024, mlp_ratio=4, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self._d = d_model
        self._max_len = max_len
        with self.name_scope():
            self.embed = Embedding(vocab_size, d_model)
            self.pos = Embedding(max_len, d_model)
            self.blocks = [
                TransformerBlock(d_model, n_heads, mlp_ratio, dropout)
                for _ in range(n_layers)]
            for i, blk in enumerate(self.blocks):
                setattr(self, f"block{i}", blk)   # register children
            self.ln_f = LayerNorm()
            self.head = Dense(vocab_size, flatten=False,
                              use_bias=False)
        self.n_layers = n_layers
        self.n_heads = n_heads

    def forward(self, tokens):
        b, l = tokens.shape
        if l > self._max_len:
            raise ValueError(
                f"sequence {l} exceeds max_len {self._max_len}")
        pos = nd.arange(l).astype("int32")
        x = self.embed(tokens) * math.sqrt(self._d)
        x = nd.broadcast_add(x, self.pos(pos).expand_dims(0))
        for blk in self.blocks:
            x = blk(x)
        return self.head(self.ln_f(x))

    def train_flops_per_token(self, seq_len):
        """Deterministic matmul-FLOPs per token for one fwd+bwd step
        (the 3x-forward rule), for MFU accounting."""
        d = self._d
        per_layer = (2 * d * 3 * d          # qkv
                     + 2 * d * d            # proj
                     + 2 * 2 * seq_len * d  # scores + att@v
                     + 2 * 2 * d * 4 * d)   # mlp up+down
        vocab = self.head._units
        fwd = self.n_layers * per_layer + 2 * d * vocab
        return 3 * fwd


def transformer_lm(vocab_size=32000, size="small", **kwargs):
    """Factory: 'small' (125M-class), 'medium' (350M-class), or pass
    explicit dims via kwargs."""
    presets = {
        "small": dict(d_model=768, n_layers=12, n_heads=12),
        "medium": dict(d_model=1024, n_layers=24, n_heads=16),
    }
    cfg = dict(presets[size]) if size in presets else {}
    cfg.update(kwargs)
    return TransformerLM(vocab_size, **cfg)
