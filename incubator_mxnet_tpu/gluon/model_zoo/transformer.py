"""Decoder-only transformer LM built from framework layers/ops.

A model family the reference era predates but today's users expect;
built TPU-first: every matmul (qkv/proj/mlp/head and the two
batch_dot attention products) lands on the MXU, shapes are static
under jit, and the causal mask is an additive constant folded by XLA.
Trains through the same paths as every other Block (Trainer,
ShardedTrainStep's kvstore='tpu' mesh step, bf16 master-weight mode);
for sequence-parallel scale-out the attention core swaps for
parallel.ring_attention (see parallel/ring_attention.py).
"""
import math
import time
from collections import OrderedDict

import numpy as np

from ... import ndarray as nd
from ... import tracing
from ..block import Block
from ..nn import Dense, Dropout, Embedding, LayerNorm

__all__ = ["TransformerLM", "TransformerBlock", "CausalSelfAttention",
           "transformer_lm"]


# --------------------------------------------------------------------------
# decode math shared by the paged-KV serving builders (serving/engine.py).
# Every formula mirrors _build_decode exactly so continuous batching
# emits the same greedy tokens as generate(); the only new ingredient
# is indirection through a block table.  Weights may be int8-quantized
# (serving/quantize.py): a {"q", "s"} dict leaf dequantizes at use.
# --------------------------------------------------------------------------


def _q_mat(w):
    """Dense matrix, dequantized if int8: ``q * s`` per out-channel.
    XLA fuses the dequant into the consuming matmul's weight read."""
    import jax.numpy as jnp
    if isinstance(w, dict):
        return w["q"].astype(jnp.float32) * w["s"][:, None]
    return w


def _q_rows(w, idx):
    """Embedding-table gather; quantized tables dequantize only the
    gathered rows (never the dense table) inside the step."""
    import jax.numpy as jnp
    if isinstance(w, dict):
        return w["q"][idx].astype(jnp.float32) * w["s"][idx][..., None]
    return w[idx]


def _jln(x, gb):
    """LayerNorm over the last axis — same epsilon/formula as the
    ``ln`` closure in _build_decode."""
    import jax.numpy as jnp
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-5) * gb[0] + gb[1]


def _ffn_rows(lw, cf, x2d):
    """Dense or MoE FFN on flattened (T, D) tokens — the same
    routing code as training and _build_decode."""
    import jax
    if "moe" in lw:
        from ...ops.moe import moe_ffn_fn
        y, _ = moe_ffn_fn(x2d, *lw["moe"], capacity_factor=cf)
        return y
    return jax.nn.relu(x2d @ _q_mat(lw["up"][0]).T + lw["up"][1]) \
        @ _q_mat(lw["down"][0]).T + lw["down"][1]


def _rope_rows(x, pos, base=10000.0):
    """RoPE for one token per batch row: x (B, H, Dh), pos (B,)
    absolute positions.  The per-slot analog of
    ``ops.matrix.rope_fn(..., offset=i)`` — identical angle formula,
    so paged decode rotates exactly like generate()'s scan step."""
    import jax.numpy as jnp
    half = x.shape[-1] // 2
    inv = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * inv[None, :]
    cos = jnp.cos(ang)[:, None, :]
    sin = jnp.sin(ang)[:, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)

# once-per-process notice when an explicit ulysses request falls back
_ULYSSES_WARNED = False


class CausalSelfAttention(Block):
    """Multi-head causal self-attention over registry ops.

    With ``seq_parallel=True`` and an ambient mesh whose 'sp' axis is
    >1 (``parallel.use_mesh``), the attention core runs as ring
    attention over the sequence axis (parallel/ring_attention.py):
    K/V blocks rotate around the ring via ppermute while each shard
    holds only L/sp of the sequence — the long-context scale-out
    path.  Falls back to exact local attention off-mesh, and both
    paths compute identical values.
    """

    def __init__(self, d_model, n_heads, seq_parallel=False,
                 rope=False, n_kv_heads=None, attn_window=0,
                 **kwargs):
        super().__init__(**kwargs)
        assert d_model % n_heads == 0
        if seq_parallel not in (False, True, "ring", "ulysses"):
            raise ValueError(
                "seq_parallel must be False/True/'ring'/'ulysses', "
                f"got {seq_parallel!r}")
        if attn_window < 0:
            raise ValueError(
                f"attn_window must be >= 0, got {attn_window}")
        if attn_window and seq_parallel:
            raise ValueError(
                "attn_window with seq_parallel is not supported — "
                "windowed long-context runs single-shard on the "
                "banded flash kernels (O(L*window) already)")
        self._window = int(attn_window)
        kv = n_kv_heads if n_kv_heads is not None else n_heads
        if kv <= 0 or n_heads % kv:
            raise ValueError(
                f"n_heads ({n_heads}) must be a positive multiple of "
                f"n_kv_heads ({kv})")
        self._rope = bool(rope)
        self._d = d_model
        self._h = n_heads
        self._kv = kv
        self._dh = d_model // n_heads
        # True == 'ring' (the default scheme; no head-count constraint)
        self._seq_parallel = "ring" if seq_parallel is True \
            else seq_parallel
        with self.name_scope():
            # grouped-query attention: kv projections carry only
            # n_kv_heads head groups (the KV cache and the k/v
            # parameter cost shrink by n_heads/n_kv_heads)
            self.qkv = Dense(d_model + 2 * kv * self._dh,
                             flatten=False, use_bias=True)
            self.proj = Dense(d_model, flatten=False, use_bias=True)

    def _ring_mesh(self, seq_len):
        """The mesh to ring over, or None to use exact local
        attention.  Ring requires: the flag, an ambient mesh with
        sp>1, a divisible sequence, and NOT an eager tape-recording
        pass — the raw-jax ring call is invisible to the imperative
        autograd tape, so eager record()/backward() must take the
        registry-op path (identical values, correct gradients); the
        compiled ShardedTrainStep path differentiates through ring
        via jax.grad and keeps it."""
        if not self._seq_parallel:
            return None
        from ... import autograd
        if autograd.is_recording():
            return None
        from ...parallel.mesh import current_mesh
        mesh = current_mesh()
        if (mesh is None or mesh.shape.get("sp", 1) <= 1
                or seq_len % mesh.shape["sp"] != 0):
            return None
        return mesh

    def forward(self, x):
        b, l, d = x.shape
        h, dh, kv = self._h, self._dh, self._kv
        kvd = kv * dh
        qkv = self.qkv(x)                   # (B, L, D + 2*KV*dh)
        q = nd.slice_axis(qkv, axis=2, begin=0, end=d)
        k = nd.slice_axis(qkv, axis=2, begin=d, end=d + kvd)
        v = nd.slice_axis(qkv, axis=2, begin=d + kvd,
                          end=d + 2 * kvd)
        if kv != h:
            # broadcast each kv group to its query heads for compute
            # (the cache/params stay at kv groups — the GQA win)
            rep = h // kv
            k = nd.repeat(k.reshape(b, l, kv, dh), repeats=rep,
                          axis=2).reshape(b, l, h * dh)
            v = nd.repeat(v.reshape(b, l, kv, dh), repeats=rep,
                          axis=2).reshape(b, l, h * dh)

        if self._rope:
            # rotate q/k per head BEFORE any sequence sharding:
            # positions are global along axis 1 (ops/matrix.rope_fn)
            q = nd._internal._rope(
                q.reshape(b, l, h, dh)).reshape(b, l, d)
            k = nd._internal._rope(
                k.reshape(b, l, h, dh)).reshape(b, l, d)

        mesh = self._ring_mesh(l)
        if mesh is not None:
            import jax
            from ...parallel import ring_attention, ulysses_attention
            # ulysses: all-to-all head sharding (needs h % sp == 0;
            # otherwise the ring scheme covers the shape)
            sp_fn = ring_attention
            if self._seq_parallel == "ulysses":
                if h % mesh.shape["sp"] == 0:
                    sp_fn = ulysses_attention
                else:
                    # once per process (a per-layer flag would log
                    # the identical line n_layers times)
                    global _ULYSSES_WARNED
                    if not _ULYSSES_WARNED:
                        from ...utils.log import get_logger
                        get_logger().warning(
                            "seq_parallel='ulysses' needs n_heads "
                            "%% sp == 0 (heads=%d, sp=%d); using "
                            "ring attention instead", h,
                            mesh.shape["sp"])
                        _ULYSSES_WARNED = True
            out = sp_fn(
                q.reshape(b, l, h, dh)._data,
                k.reshape(b, l, h, dh)._data,
                v.reshape(b, l, h, dh)._data, mesh, causal=True)
            if not isinstance(out, jax.core.Tracer):
                # eager: gather off the mesh so downstream ops can mix
                # with single-device parameters (under jit the step's
                # shardings govern instead)
                out = jax.device_put(
                    out, list(x._data.devices())[0])
            return self.proj(nd.NDArray(out).reshape(b, l, d))

        def heads(t):                              # (B, L, D)->(B*H, L, Dh)
            return t.reshape(b, l, h, dh).transpose(
                (0, 2, 1, 3)).reshape(b * h, l, dh)

        q, k, v = heads(q), heads(k), heads(v)
        if self._use_flash():
            # Pallas online-softmax kernel (ops/flash.py): no L x L
            # score tensor in HBM; registry op, so the tape and the
            # compiled paths both differentiate it
            out = nd._internal._flash_attention(
                q, k, v, causal=True, window=self._window)
        else:
            scores = nd.batch_dot(q, k, transpose_b=True) \
                / math.sqrt(dh)
            diff = np.subtract.outer(np.arange(l), np.arange(l))
            banned = diff < 0      # future
            if self._window:
                # sliding window: query i sees (i - window, i]
                banned |= diff >= self._window
            mask = nd.array(
                np.where(banned, -1e9, 0.0).astype(np.float32))
            scores = nd.broadcast_add(scores, mask.expand_dims(0))
            att = nd.softmax(scores, axis=-1)
            out = nd.batch_dot(att, v)             # (B*H, L, Dh)
        out = out.reshape(b, h, l, dh).transpose(
            (0, 2, 1, 3)).reshape(b, l, d)
        return self.proj(out)

    @staticmethod
    def _use_flash():
        import os

        import jax
        flag = os.environ.get("MXTPU_FLASH", "auto")
        if flag in ("1", "0"):
            return flag == "1"
        return jax.default_backend() == "tpu"


class MoEFFN(Block):
    """Mixture-of-Experts FFN (GShard top-2; see ops/moe.py).

    Expert weights are STACKED over a leading expert dimension —
    (E, H, D)/(E, D, H) — so the expert compute is one batched MXU
    contraction and the 'ep' mesh axis shards dimension 0 (the
    expert-parallel rules in parallel/sharding.py); GSPMD then
    derives the token all-to-alls.  ``forward`` returns the output
    AND exposes the load-balance aux loss as ``self.last_aux`` (read
    it in the same forward pass; add ~1e-2 of it to the loss).
    """

    def __init__(self, d_model, num_experts, hidden,
                 capacity_factor=1.25, **kwargs):
        super().__init__(**kwargs)
        self._cf = float(capacity_factor)
        self.num_experts = num_experts
        with self.name_scope():
            self.router_weight = self.params.get(
                "router_weight", shape=(num_experts, d_model))
            self.expert_up_weight = self.params.get(
                "expert_up_weight", shape=(num_experts, hidden,
                                           d_model))
            self.expert_up_bias = self.params.get(
                "expert_up_bias", shape=(num_experts, hidden),
                init="zeros")
            self.expert_down_weight = self.params.get(
                "expert_down_weight", shape=(num_experts, d_model,
                                             hidden))
            self.expert_down_bias = self.params.get(
                "expert_down_bias", shape=(num_experts, d_model),
                init="zeros")

    def forward(self, x):                      # (B, L, D)
        b, l, d = x.shape
        y, aux = nd._internal._moe_ffn(
            x.reshape(b * l, d), self.router_weight.data(),
            self.expert_up_weight.data(),
            self.expert_up_bias.data(),
            self.expert_down_weight.data(),
            self.expert_down_bias.data(),
            capacity_factor=self._cf)
        self.last_aux = aux
        return y.reshape(b, l, d)


class TransformerBlock(Block):
    """Pre-norm attention + MLP with residuals (GPT-2 layout).

    ``moe_experts > 0`` swaps the dense MLP for a top-2-routed
    Mixture-of-Experts FFN (MoEFFN); the block then exposes the
    router's load-balance loss as ``self.last_aux``.
    """

    def __init__(self, d_model, n_heads, mlp_ratio=4, dropout=0.0,
                 seq_parallel=False, moe_experts=0,
                 moe_capacity_factor=1.25, rope=False,
                 n_kv_heads=None, attn_window=0, **kwargs):
        super().__init__(**kwargs)
        self.moe_experts = moe_experts
        with self.name_scope():
            self.ln1 = LayerNorm()
            self.attn = CausalSelfAttention(d_model, n_heads,
                                            seq_parallel=seq_parallel,
                                            rope=rope,
                                            n_kv_heads=n_kv_heads,
                                            attn_window=attn_window)
            self.ln2 = LayerNorm()
            if moe_experts:
                self.moe = MoEFFN(d_model, moe_experts,
                                  mlp_ratio * d_model,
                                  capacity_factor=moe_capacity_factor)
            else:
                self.up = Dense(mlp_ratio * d_model, flatten=False,
                                activation="relu")
                self.down = Dense(d_model, flatten=False)
            self.drop = Dropout(dropout)

    def forward(self, x):
        x = x + self.drop(self.attn(self.ln1(x)))
        if self.moe_experts:
            y = self.moe(self.ln2(x))
            self.last_aux = self.moe.last_aux
            return x + self.drop(y)
        return x + self.drop(self.down(self.up(self.ln2(x))))


class TransformerLM(Block):
    """Token-in, logits-out decoder LM.

    Parameters: vocab_size, d_model, n_layers, n_heads, max_len
    (learned positions), mlp_ratio, dropout.
    """

    def __init__(self, vocab_size, d_model=512, n_layers=6,
                 n_heads=8, max_len=1024, mlp_ratio=4, dropout=0.0,
                 seq_parallel=False, moe_experts=0,
                 moe_capacity_factor=1.25, pos="learned",
                 n_kv_heads=None, attn_window=0, **kwargs):
        super().__init__(**kwargs)
        if pos not in ("learned", "rope"):
            raise ValueError(
                f"pos must be 'learned' or 'rope', got {pos!r}")
        self._d = d_model
        self._max_len = max_len
        self._mlp_ratio = mlp_ratio
        self._pos_kind = pos
        self.moe_experts = moe_experts
        with self.name_scope():
            self.embed = Embedding(vocab_size, d_model)
            if pos == "learned":
                self.pos = Embedding(max_len, d_model)
            self.blocks = [
                TransformerBlock(d_model, n_heads, mlp_ratio, dropout,
                                 seq_parallel=seq_parallel,
                                 moe_experts=moe_experts,
                                 moe_capacity_factor=
                                 moe_capacity_factor,
                                 rope=(pos == "rope"),
                                 n_kv_heads=n_kv_heads,
                                 attn_window=attn_window)
                for _ in range(n_layers)]
            for i, blk in enumerate(self.blocks):
                setattr(self, f"block{i}", blk)   # register children
            self.ln_f = LayerNorm()
            self.head = Dense(vocab_size, flatten=False,
                              use_bias=False)
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.n_kv_heads = n_kv_heads or n_heads
        self.attn_window = int(attn_window)

    def forward(self, tokens):
        """Logits (B, L, V); with ``moe_experts`` the return is
        ``[logits, aux]`` where aux is the summed router load-balance
        loss — add ``~1e-2 * aux`` to the training loss."""
        b, l = tokens.shape
        if l > self._max_len:
            raise ValueError(
                f"sequence {l} exceeds max_len {self._max_len}")
        x = self.embed(tokens) * math.sqrt(self._d)
        if self._pos_kind == "learned":
            pos = nd.arange(l).astype("int32")
            x = nd.broadcast_add(x, self.pos(pos).expand_dims(0))
        aux = None
        for blk in self.blocks:
            x = blk(x)
            if self.moe_experts:
                aux = blk.last_aux if aux is None \
                    else aux + blk.last_aux
        logits = self.head(self.ln_f(x))
        return [logits, aux] if self.moe_experts else logits

    # ------------------------------------------------------------ decode
    _GEN_CACHE_MAX = 16   # compiled decode executables kept (LRU)

    def generate(self, tokens, max_new_tokens, temperature=0.0,
                 top_k=0, top_p=1.0, rng=None):
        """Autoregressive decode with a KV cache, TPU-native: ONE
        batched prefill forward seeds the cache for the whole prompt,
        then ONE ``lax.scan`` emits the new tokens.  Static shapes
        throughout; compiled once per (batch, prompt_len,
        max_new_tokens, sampling-config) signature (bounded FIFO of
        executables — pad prompts to a few fixed lengths and keep the
        sampling config stable to maximise compile reuse).

        tokens : (B, P) int NDArray/numpy prompt
        temperature : 0 -> greedy argmax, >0 -> categorical sample
        top_k : keep only the k highest-probability tokens (0 = all)
        top_p : nucleus sampling — keep the smallest set of tokens
            whose cumulative probability exceeds top_p (1.0 = all)
        returns (B, P + max_new_tokens) int32 NDArray
        """
        import jax
        import jax.numpy as jnp

        if top_k < 0:
            raise ValueError(f"top_k must be >= 0 (got {top_k})")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1] (got {top_p})")
        toks_np = np.asarray(
            tokens.asnumpy() if hasattr(tokens, "asnumpy")
            else tokens).astype(np.int32)
        b, p = toks_np.shape
        total = p + int(max_new_tokens)
        if total > self._max_len:
            raise ValueError(
                f"prompt+new = {total} exceeds max_len "
                f"{self._max_len}")

        from ..parameter import DeferredInitializationError
        try:
            wts = self._decode_weights()
        except DeferredInitializationError:
            # deferred-init params (LayerNorm shapes): settle with a
            # tiny probe forward, as functionalize does
            from ... import autograd
            with autograd.pause():
                self.forward(nd.NDArray(jnp.zeros((1, 1), jnp.int32)))
            wts = self._decode_weights()

        sampling = temperature > 0
        # greedy ignores the sampling filters: normalize them out of
        # the compile key so greedy callers share one executable
        key = (b, p, int(max_new_tokens), sampling,
               int(top_k) if sampling else 0,
               float(top_p) if sampling else 1.0)
        cache = getattr(self, "_gen_cache", None)
        if not isinstance(cache, OrderedDict):
            # true LRU, not FIFO: an alternating pair of hot
            # signatures at capacity must not thrash recompiles
            cache = self._gen_cache = OrderedDict(cache or {})
        fn = cache.get(key)
        missed = fn is None
        t0 = time.monotonic()
        if missed:
            if len(cache) >= self._GEN_CACHE_MAX:
                cache.popitem(last=False)       # least recently used
            fn = cache[key] = jax.jit(self._build_decode(
                b, p, int(max_new_tokens), temperature > 0,
                top_k=int(top_k), top_p=float(top_p)))
        else:
            cache.move_to_end(key)              # refresh on hit
        if rng is None:
            rng = jax.random.PRNGKey(0)
        out = fn(wts, jnp.asarray(toks_np),
                 jnp.asarray(float(temperature or 1.0), jnp.float32),
                 rng)
        if missed:
            # jax.jit traces lazily: build + first call is the real
            # compile wall time this signature cost (compile ledger
            # attributes the miss — shape vs decode-config change)
            tracing.compile_ledger("transformer_generate").record(
                {"shape": (b, p),
                 "static_arg": (int(max_new_tokens), sampling,
                                key[4], key[5])},
                time.monotonic() - t0)
        return nd.NDArray(out)

    def _decode_weights(self):
        def w(param):
            return param.data()._data

        layers = []
        for blk in self.blocks:
            lw = dict(
                ln1=(w(blk.ln1.gamma), w(blk.ln1.beta)),
                qkv=(w(blk.attn.qkv.weight), w(blk.attn.qkv.bias)),
                proj=(w(blk.attn.proj.weight), w(blk.attn.proj.bias)),
                ln2=(w(blk.ln2.gamma), w(blk.ln2.beta)))
            if blk.moe_experts:
                lw["moe"] = (w(blk.moe.router_weight),
                             w(blk.moe.expert_up_weight),
                             w(blk.moe.expert_up_bias),
                             w(blk.moe.expert_down_weight),
                             w(blk.moe.expert_down_bias))
            else:
                lw["up"] = (w(blk.up.weight), w(blk.up.bias))
                lw["down"] = (w(blk.down.weight), w(blk.down.bias))
            layers.append(lw)
        wts = dict(embed=w(self.embed.weight),
                   ln_f=(w(self.ln_f.gamma), w(self.ln_f.beta)),
                   head=w(self.head.weight), layers=layers)
        if self._pos_kind == "learned":
            wts["pos"] = w(self.pos.weight)
        return wts

    def _build_decode(self, b, p, max_new, sample, top_k=0,
                      top_p=1.0):
        import jax
        import jax.numpy as jnp
        from jax import lax

        d, h = self._d, self.n_heads
        dh = d // h
        kv = self.n_kv_heads
        rep = h // kv
        kvd = kv * dh
        total = p + max_new
        scale = math.sqrt(d)
        use_rope = self._pos_kind == "rope"
        window = self.attn_window
        from ...ops.matrix import rope_fn

        # LayerNorm / FFN math is the module-level _jln/_ffn_rows —
        # one implementation shared with the paged serving builders,
        # so generate() and the serving engine can never diverge.
        # Capacity factors are STATIC per layer (compile-time), not
        # part of the traced weights pytree.
        cfs = [blk.moe._cf if blk.moe_experts else None
               for blk in self.blocks]

        def restrict(logits):
            """top-k / nucleus filtering on (B, V) logits."""
            if top_k and top_k < logits.shape[-1]:
                kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if top_p < 1.0:
                sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(sorted_l, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # number of tokens needed to reach top_p (>= 1)
                k_eff = jnp.maximum(
                    jnp.sum(cum - probs < top_p, axis=-1,
                            keepdims=True), 1)
                cutoff = jnp.take_along_axis(sorted_l, k_eff - 1,
                                             axis=-1)
                logits = jnp.where(logits < cutoff, -jnp.inf, logits)
            return logits

        def pick(logits, temp, rng):
            if sample:
                rng, sub = jax.random.split(rng)
                nxt = jax.random.categorical(
                    sub, restrict(logits / temp))
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return nxt.astype(jnp.int32), rng

        def prefill(wts, prompt):
            """Batched forward over the whole prompt: seeds the KV
            caches in one pass and returns the last position's
            logits (same math as the per-token step)."""
            x = wts["embed"][prompt] * scale       # (B, P, D)
            if not use_rope:
                x = x + wts["pos"][jnp.arange(p)]
            diff = jnp.arange(p)[:, None] - jnp.arange(p)[None, :]
            mask = diff >= 0
            if window:
                # decode must mask exactly like training
                mask &= diff < window
            caches = []
            for lw, cf in zip(wts["layers"], cfs):
                xa = _jln(x, lw["ln1"])
                qkv = xa @ lw["qkv"][0].T + lw["qkv"][1]
                q = qkv[..., :d].reshape(b, p, h, dh)
                k = qkv[..., d:d + kvd].reshape(b, p, kv, dh)
                v = qkv[..., d + kvd:].reshape(b, p, kv, dh)
                if use_rope:
                    q, k = rope_fn(q), rope_fn(k)
                q = q.transpose(0, 2, 1, 3)
                k = k.transpose(0, 2, 1, 3)
                v = v.transpose(0, 2, 1, 3)
                # GQA: the cache holds only kv head groups
                kc = jnp.zeros((b, kv, total, dh),
                               jnp.float32).at[:, :, :p].set(k)
                vc = jnp.zeros((b, kv, total, dh),
                               jnp.float32).at[:, :, :p].set(v)
                # grouped einsum straight against the kv-group
                # tensors: the h-head repeat is never materialized
                qg = q.reshape(b, kv, rep, p, dh)
                s = jnp.einsum("bkrqd,bkcd->bkrqc", qg, k) \
                    / math.sqrt(dh)
                att = jax.nn.softmax(
                    jnp.where(mask[None, None, None], s, -1e9),
                    axis=-1)
                o = jnp.einsum("bkrqc,bkcd->bkrqd", att, v)
                o = o.reshape(b, h, p, dh) \
                    .transpose(0, 2, 1, 3).reshape(b, p, d)
                x = x + o @ lw["proj"][0].T + lw["proj"][1]
                xm = _jln(x, lw["ln2"])
                x = x + _ffn_rows(lw, cf, xm.reshape(b * p, d)) \
                    .reshape(b, p, d)
                caches.append((kc, vc))
            logits = _jln(x[:, -1], wts["ln_f"]) @ wts["head"].T
            return caches, logits

        def decode(wts, prompt, temp, rng):
            caches, logits = prefill(wts, prompt)
            first, rng = pick(logits, temp, rng)
            toks = jnp.zeros((b, total), jnp.int32)
            toks = toks.at[:, :p].set(prompt)
            toks = toks.at[:, p].set(first)

            def step(carry, i):
                toks, caches, rng = carry
                tok = lax.dynamic_index_in_dim(toks, i, axis=1,
                                               keepdims=False)
                x = wts["embed"][tok] * scale
                if not use_rope:
                    x = x + wts["pos"][i]
                new_caches = []
                for (lw, cf), (kc, vc) in zip(
                        zip(wts["layers"], cfs), caches):
                    xa = _jln(x, lw["ln1"])
                    qkv = xa @ lw["qkv"][0].T + lw["qkv"][1]
                    q = qkv[..., :d]
                    k = qkv[..., d:d + kvd]
                    v = qkv[..., d + kvd:]
                    if use_rope:
                        # this token sits at absolute position i
                        q = rope_fn(q.reshape(b, 1, h, dh),
                                    offset=i).reshape(b, h, dh)
                        k = rope_fn(k.reshape(b, 1, kv, dh),
                                    offset=i).reshape(b, kv, dh)
                    else:
                        q = q.reshape(b, h, dh)
                        k = k.reshape(b, kv, dh)
                    kc = lax.dynamic_update_index_in_dim(
                        kc, k, i, axis=2)
                    vc = lax.dynamic_update_index_in_dim(
                        vc, v.reshape(b, kv, dh), i, axis=2)
                    qg = q.reshape(b, kv, rep, dh)
                    s = jnp.einsum("bkrd,bkcd->bkrc", qg, kc) \
                        / math.sqrt(dh)
                    cpos = jnp.arange(total)[None, None, None]
                    keep = cpos <= i
                    if window:
                        keep &= cpos > i - window
                    s = jnp.where(keep, s, -1e9)
                    att = jax.nn.softmax(s, axis=-1)
                    o = jnp.einsum("bkrc,bkcd->bkrd", att, vc) \
                        .reshape(b, h, dh)
                    x = x + o.reshape(b, d) @ lw["proj"][0].T \
                        + lw["proj"][1]
                    xm = _jln(x, lw["ln2"])
                    x = x + _ffn_rows(lw, cf, xm)
                    new_caches.append((kc, vc))
                logits = _jln(x, wts["ln_f"]) @ wts["head"].T
                nxt, rng = pick(logits, temp, rng)
                toks = lax.dynamic_update_index_in_dim(
                    toks, nxt, i + 1, axis=1)
                return (toks, new_caches, rng), None

            # positions p .. total-2 each consume the token at i and
            # emit the one at i+1 (the prefill already emitted p)
            if max_new > 1:
                (toks, _, _), _ = lax.scan(
                    step, (toks, caches, rng),
                    jnp.arange(p, total - 1))
            return toks

        return decode

    # ---------------------------------------------------- paged decode
    # Block-table variants of prefill/step for the serving tier
    # (serving/engine.py, docs/serving.md).  KV lives in fixed pools
    # of shape (num_blocks, block_size, kv_heads, head_dim) per
    # layer; a request's context is the ordered block-id row it owns.
    # Scatter/gather by block id happens INSIDE the jitted function,
    # so admission/retirement never changes the traced signature —
    # one compiled step per (max_batch, max_blocks) forever.

    def _check_paged(self):
        if self.attn_window:
            raise NotImplementedError(
                "paged serving over sliding-window attention is not "
                "implemented — serve attn_window=0 models, or decode "
                "via generate()")
        if self.moe_experts:
            # top-2 routing sets expert capacity from the BATCH of
            # tokens in flight: concurrent slots contend for
            # capacity a sequential generate() call never sees, so
            # served logits would depend on batch occupancy and the
            # greedy-equivalence contract would silently break
            raise NotImplementedError(
                "paged serving of MoE models is not implemented — "
                "shared expert capacity makes logits depend on "
                "batchmates; decode MoE models via generate()")

    def _build_paged_prefill(self, suffix_len, max_blocks,
                             block_size):
        """Suffix prefill over the block-table cache.

        One traced signature per padded suffix length: embeds ``S``
        suffix tokens at absolute positions ``n_past + i``, scatters
        their K/V into the request's blocks, and attends over the
        whole block-table context — ``n_past = 0`` is a full
        prefill; ``n_past > 0`` resumes after a prefix-cache hit
        without recomputing the shared blocks.  Rows past
        ``true_len`` are padding: they scatter to the scratch block
        (id 0) and their outputs are discarded.

        Returns ``prefill(wts, kpools, vpools, table, n_past,
        tokens, true_len) -> (kpools, vpools, next_token, logits)``
        where ``next_token`` is the greedy argmax after the last
        real suffix token.
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        self._check_paged()
        d, h = self._d, self.n_heads
        dh = d // h
        kv = self.n_kv_heads
        rep = h // kv
        kvd = kv * dh
        scale = math.sqrt(d)
        use_rope = self._pos_kind == "rope"
        max_len = self._max_len
        from ...ops.matrix import rope_fn
        S, MB, bs = int(suffix_len), int(max_blocks), int(block_size)
        C = MB * bs
        cfs = [blk.moe._cf if blk.moe_experts else None
               for blk in self.blocks]

        def prefill(wts, kpools, vpools, table, n_past, tokens,
                    true_len):
            x = _q_rows(wts["embed"], tokens) * scale       # (S, D)
            pos = n_past + jnp.arange(S)
            if not use_rope:
                x = x + _q_rows(wts["pos"],
                                jnp.minimum(pos, max_len - 1))
            valid = jnp.arange(S) < true_len
            wpos = jnp.where(valid, pos, 0)
            blk = jnp.where(
                valid, table[jnp.minimum(wpos // bs, MB - 1)], 0)
            off = wpos % bs
            keep = jnp.arange(C)[None, :] <= pos[:, None]   # (S, C)
            new_k, new_v = [], []
            for li, (lw, cf) in enumerate(zip(wts["layers"], cfs)):
                xa = _jln(x, lw["ln1"])
                qkvm = xa @ _q_mat(lw["qkv"][0]).T + lw["qkv"][1]
                q = qkvm[:, :d].reshape(S, h, dh)
                k = qkvm[:, d:d + kvd].reshape(S, kv, dh)
                v = qkvm[:, d + kvd:].reshape(S, kv, dh)
                if use_rope:
                    q = rope_fn(q[None], offset=n_past)[0]
                    k = rope_fn(k[None], offset=n_past)[0]
                kp = kpools[li].at[blk, off].set(k)
                vp = vpools[li].at[blk, off].set(v)
                # gather the whole context back through the table:
                # lane c of the flattened (C,) axis IS absolute
                # position c, because the row is ordered by logical
                # block index
                kc = kp[table].reshape(C, kv, dh).transpose(1, 0, 2)
                vc = vp[table].reshape(C, kv, dh).transpose(1, 0, 2)
                qg = q.transpose(1, 0, 2).reshape(kv, rep, S, dh)
                s = jnp.einsum("krsd,kcd->krsc", qg, kc) \
                    / math.sqrt(dh)
                att = jax.nn.softmax(
                    jnp.where(keep[None, None], s, -1e9), axis=-1)
                o = jnp.einsum("krsc,kcd->krsd", att, vc)
                o = o.reshape(h, S, dh).transpose(1, 0, 2) \
                    .reshape(S, d)
                x = x + o @ _q_mat(lw["proj"][0]).T + lw["proj"][1]
                xm = _jln(x, lw["ln2"])
                x = x + _ffn_rows(lw, cf, xm)
                new_k.append(kp)
                new_v.append(vp)
            xl = lax.dynamic_index_in_dim(x, true_len - 1, 0,
                                          keepdims=False)
            logits = _jln(xl, wts["ln_f"]) @ _q_mat(wts["head"]).T
            nxt = jnp.argmax(logits).astype(jnp.int32)
            return new_k, new_v, nxt, logits

        return prefill

    def _build_paged_step(self, max_batch, max_blocks, block_size):
        """One continuous-batching decode step over the block pool.

        Feeds every slot's newest token at its own position, scatters
        the new K/V through each slot's block-table row, and attends
        over the gathered context.  Inactive slots ride along with
        ``n_past = 0`` and an all-scratch row — their writes land in
        block 0 and their outputs are ignored by the host — so the
        step needs NO liveness branch and admission/retirement reuse
        the one compiled executable.

        Returns ``step(wts, kpools, vpools, tables, n_past, tokens)
        -> (kpools, vpools, next_tokens, logits)`` (greedy argmax
        per slot).
        """
        import jax
        import jax.numpy as jnp

        self._check_paged()
        d, h = self._d, self.n_heads
        dh = d // h
        kv = self.n_kv_heads
        rep = h // kv
        kvd = kv * dh
        scale = math.sqrt(d)
        use_rope = self._pos_kind == "rope"
        B, MB, bs = int(max_batch), int(max_blocks), int(block_size)
        C = MB * bs
        cfs = [blk.moe._cf if blk.moe_experts else None
               for blk in self.blocks]

        def step(wts, kpools, vpools, tables, n_past, tokens):
            x = _q_rows(wts["embed"], tokens) * scale       # (B, D)
            if not use_rope:
                x = x + _q_rows(wts["pos"], n_past)
            blk = jnp.take_along_axis(
                tables, (n_past // bs)[:, None], axis=1)[:, 0]
            off = n_past % bs
            keep = jnp.arange(C)[None, :] <= n_past[:, None]
            new_k, new_v = [], []
            for li, (lw, cf) in enumerate(zip(wts["layers"], cfs)):
                xa = _jln(x, lw["ln1"])
                qkvm = xa @ _q_mat(lw["qkv"][0]).T + lw["qkv"][1]
                q = qkvm[:, :d].reshape(B, h, dh)
                k = qkvm[:, d:d + kvd].reshape(B, kv, dh)
                v = qkvm[:, d + kvd:].reshape(B, kv, dh)
                if use_rope:
                    q = _rope_rows(q, n_past)
                    k = _rope_rows(k, n_past)
                kp = kpools[li].at[blk, off].set(k)
                vp = vpools[li].at[blk, off].set(v)
                kc = kp[tables].reshape(B, C, kv, dh) \
                    .transpose(0, 2, 1, 3)          # (B, kv, C, dh)
                vc = vp[tables].reshape(B, C, kv, dh) \
                    .transpose(0, 2, 1, 3)
                qg = q.reshape(B, kv, rep, dh)
                s = jnp.einsum("bkrd,bkcd->bkrc", qg, kc) \
                    / math.sqrt(dh)
                att = jax.nn.softmax(
                    jnp.where(keep[:, None, None, :], s, -1e9),
                    axis=-1)
                o = jnp.einsum("bkrc,bkcd->bkrd", att, vc) \
                    .reshape(B, d)
                x = x + o @ _q_mat(lw["proj"][0]).T + lw["proj"][1]
                xm = _jln(x, lw["ln2"])
                x = x + _ffn_rows(lw, cf, xm)
                new_k.append(kp)
                new_v.append(vp)
            logits = _jln(x, wts["ln_f"]) @ _q_mat(wts["head"]).T
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return new_k, new_v, nxt, logits

        return step

    def train_flops_per_token(self, seq_len):
        """Deterministic matmul-FLOPs per token for one fwd+bwd step
        (the 3x-forward rule), for MFU accounting.  MoE: each token
        runs TWO experts' FFNs (top-2 routing) plus the router."""
        d = self._d
        hid = self._mlp_ratio * d
        if self.moe_experts:
            e = self.moe_experts
            # top-2: 2x one expert's up+down, + router matmul
            mlp = 2 * (2 * 2 * d * hid) + 2 * d * e
        else:
            mlp = 2 * 2 * d * hid          # dense up+down
        kvd = self.n_kv_heads * (d // self.n_heads)
        att_span = min(seq_len, self.attn_window) \
            if self.attn_window else seq_len
        per_layer = (2 * d * (d + 2 * kvd)  # qkv (GQA-sized)
                     + 2 * d * d            # proj
                     + 2 * 2 * att_span * d  # scores + att@v (banded)
                     + mlp)
        vocab = self.head._units
        fwd = self.n_layers * per_layer + 2 * d * vocab
        return 3 * fwd

    def decode_flops_per_token(self, context_len):
        """Matmul FLOPs to decode ONE token against a KV cache of
        ``context_len`` entries (no 3x rule — forward only), for
        ``serving_mfu`` accounting (docs/observability.md)."""
        d = self._d
        hid = self._mlp_ratio * d
        if self.moe_experts:
            e = self.moe_experts
            mlp = 2 * (2 * 2 * d * hid) + 2 * d * e
        else:
            mlp = 2 * 2 * d * hid
        kvd = self.n_kv_heads * (d // self.n_heads)
        span = min(context_len, self.attn_window) \
            if self.attn_window else context_len
        per_layer = (2 * d * (d + 2 * kvd)
                     + 2 * d * d
                     + 2 * 2 * span * d
                     + mlp)
        vocab = self.head._units
        return self.n_layers * per_layer + 2 * d * vocab


def transformer_lm(vocab_size=32000, size="small", **kwargs):
    """Factory: 'small' (125M-class), 'medium' (350M-class),
    'modern' (the rope + grouped-query configuration today's
    decoder LMs ship with), or pass explicit dims via kwargs."""
    presets = {
        "small": dict(d_model=768, n_layers=12, n_heads=12),
        "medium": dict(d_model=1024, n_layers=24, n_heads=16),
        "modern": dict(d_model=768, n_layers=12, n_heads=12,
                       n_kv_heads=4, pos="rope"),
    }
    if size not in presets:
        raise ValueError(
            f"unknown size {size!r}; presets: {sorted(presets)} "
            "(pass explicit dims via kwargs with any preset)")
    cfg = dict(presets[size])
    cfg.update(kwargs)
    return TransformerLM(vocab_size, **cfg)
