"""Gluon RNN API (ref: python/mxnet/gluon/rnn/) — cells and fused
layers arrive with the RNN milestone (lax.scan kernels)."""
__all__ = []
