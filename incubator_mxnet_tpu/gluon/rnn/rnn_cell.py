"""Gluon recurrent cells (ref: python/mxnet/gluon/rnn/rnn_cell.py —
RecurrentCell:96, RNNCell:273, LSTMCell:373, GRUCell:485,
SequentialRNNCell:605, DropoutCell:677, ModifierCell:728,
ZoneoutCell:770, ResidualCell:815, BidirectionalCell:849).

Cells are fine-grained HybridBlocks: one step = a couple of
FullyConnected ops, so an unrolled/hybridized cell compiles into a
single fused XLA loop body.  For whole-sequence speed prefer the fused
layers in rnn_layer.py (single lax.scan kernel).
"""

from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell",
           "LSTMCell", "GRUCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of (N,C) steps or a merged tensor.
    Returns (inputs, axis, batch_size)."""
    assert layout in ("TNC", "NTC"), f"bad layout {layout}"
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        assert length is None or len(inputs) == length
        # per-step arrays have already dropped the T axis: always (N,C)
        batch_size = inputs[0].shape[0]
        seq = list(inputs)
    else:
        batch_size = inputs.shape[batch_axis]
        L = inputs.shape[axis]
        assert length is None or L == length
        seq = _split_steps(inputs, L, axis)
    return seq, axis, batch_size


def _split_steps(x, num, axis):
    """Split along time and drop the time axis: per-step (N,C)."""
    from ... import nd
    outs = nd.SliceChannel(x, num_outputs=num, axis=axis,
                           squeeze_axis=True)
    return outs if isinstance(outs, (list, tuple)) else [outs]


def _merge(outputs, axis):
    from ... import nd
    return nd.stack(*outputs, axis=axis)


class RecurrentCell(HybridBlock):
    """Base recurrent cell (ref: rnn_cell.py RecurrentCell:96)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children:
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (ref: rnn_cell.py begin_state)."""
        assert not self._modified, \
            "After applying modifier cells, call the modifier's " \
            "begin_state instead"
        from ... import nd
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **info, **kwargs)
                          if "shape" not in kwargs else func(**kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell for `length` steps (ref: rnn_cell.py
        unroll:190)."""
        self.reset()
        seq, axis, batch_size = _format_sequence(length, inputs, layout,
                                                 merge_outputs)
        states = begin_state if begin_state is not None else \
            self.begin_state(batch_size=batch_size)
        outputs = []
        all_states = []
        for i in range(len(seq)):
            out, states = self(seq[i], states)
            outputs.append(out)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            from ... import nd
            # final state of each sequence = its state at the last
            # VALID step, not after the padding (ref: rnn_cell.py
            # unroll — SequenceLast over stacked per-step states)
            states = [nd.SequenceLast(
                          _merge([s[i] for s in all_states], 0),
                          valid_length, use_sequence_length=True,
                          axis=0)
                      for i in range(len(states))]
            merged = _merge(outputs, axis)
            merged = nd.SequenceMask(merged, valid_length,
                                     use_sequence_length=True,
                                     axis=axis)
            if merge_outputs is False:
                outputs = _split_steps(merged, len(seq), axis)
            else:
                outputs = merged
            return outputs, states
        if merge_outputs is None or merge_outputs:
            outputs = _merge(outputs, axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell):
    """Cells whose step is a hybrid_forward (ref: rnn_cell.py:264)."""

    def forward(self, inputs, states):
        self._counter += 1
        params = self._materialized_params([inputs])
        from ... import nd as F
        return self.hybrid_forward(F, inputs, states, **params)

    def __call__(self, inputs, states):
        return self.forward(inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class _BaseDenseCell(HybridRecurrentCell):
    """Shared param plumbing for RNN/LSTM/GRU cells."""

    _gates = 1

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        G = self._gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(G * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(G * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(G * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(G * hidden_size,),
                init=h2h_bias_initializer)

    def shape_from_input(self, x, *rest):
        self.i2h_weight.shape = (self._gates * self._hidden_size,
                                 x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]


class RNNCell(_BaseDenseCell):
    """Elman RNN cell (ref: rnn_cell.py RNNCell:273)."""

    _gates = 1

    def __init__(self, hidden_size, activation="tanh", **kwargs):
        super().__init__(hidden_size, **kwargs)
        self._activation = activation

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight,
                       h2h_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(_BaseDenseCell):
    """LSTM cell, gate order i,f,c,o (ref: rnn_cell.py LSTMCell:373)."""

    _gates = 4

    def _alias(self):
        return "lstm"

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, states, i2h_weight,
                       h2h_weight, i2h_bias, h2h_bias):
        H = self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * H)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * H)
        gates = i2h + h2h
        g = F.SliceChannel(gates, num_outputs=4, axis=-1)
        in_gate = F.Activation(g[0], act_type="sigmoid")
        forget_gate = F.Activation(g[1], act_type="sigmoid")
        in_transform = F.Activation(g[2], act_type="tanh")
        out_gate = F.Activation(g[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(_BaseDenseCell):
    """GRU cell, gate order r,z,n (ref: rnn_cell.py GRUCell:485)."""

    _gates = 3

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight,
                       h2h_weight, i2h_bias, h2h_bias):
        H = self._hidden_size
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * H)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * H)
        ig = F.SliceChannel(i2h, num_outputs=3, axis=-1)
        hg = F.SliceChannel(h2h, num_outputs=3, axis=-1)
        reset_gate = F.Activation(ig[0] + hg[0], act_type="sigmoid")
        update_gate = F.Activation(ig[1] + hg[1], act_type="sigmoid")
        next_h_tmp = F.Activation(ig[2] + reset_gate * hg[2],
                                  act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + \
            update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells applied in order (ref: rnn_cell.py:605)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            n = len(cell.state_info())
            cell_states = states[p:p + n]
            p += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class DropoutCell(RecurrentCell):
    """Dropout on the cell stream (ref: rnn_cell.py DropoutCell:677)."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.rate = rate

    def state_info(self, batch_size=0):
        return []

    def __call__(self, inputs, states):
        self._counter += 1
        if self.rate > 0:
            from ... import nd
            inputs = nd.Dropout(inputs, p=self.rate)
        return inputs, states


class ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (ref: rnn_cell.py:728)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "cell already modified by another modifier"
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias() + "_",
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (ref: rnn_cell.py ZoneoutCell:770)."""

    def __init__(self, base_cell, zoneout_outputs=0.0,
                 zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout; wrap the " \
            "inner cells instead"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        from ... import nd, autograd
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        p_out, p_st = self.zoneout_outputs, self.zoneout_states

        def mask(p, like):
            return nd.Dropout(nd.ones_like(like), p=p)

        prev_output = self._prev_output if self._prev_output is not None \
            else nd.zeros_like(next_output)
        if autograd.is_training():
            output = nd.where(mask(p_out, next_output), next_output,
                              prev_output) if p_out != 0.0 \
                else next_output
            states = [nd.where(mask(p_st, ns), ns, s)
                      for s, ns in zip(states, next_states)] \
                if p_st != 0.0 else next_states
        else:
            # inference: expectation
            output = (1 - p_out) * next_output + p_out * prev_output \
                if p_out != 0.0 else next_output
            states = [(1 - p_st) * ns + p_st * s
                      for s, ns in zip(states, next_states)] \
                if p_st != 0.0 else next_states
        self._prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds the input to the output (ref: rnn_cell.py:815)."""

    def _alias(self):
        return "residual"

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    """Runs two cells over the sequence in both directions (ref:
    rnn_cell.py BidirectionalCell:849).  Step-call is invalid; only
    unroll works (matches reference)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cells cannot be stepped; use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        seq, axis, batch_size = _format_sequence(length, inputs,
                                                 layout, None)
        states = begin_state if begin_state is not None else \
            self.begin_state(batch_size=batch_size)
        l_cell, r_cell = self._children
        n_l = len(l_cell.state_info(batch_size))
        from ... import nd
        step_layout = "TNC" if axis == 0 else "NTC"
        l_out, l_states = l_cell.unroll(
            length, seq, states[:n_l], layout=step_layout,
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            rev = list(reversed(seq))
        else:
            # sequence-aware reverse: each sequence's valid prefix is
            # reversed in place so the r_cell sees valid data first
            # (ref: rnn_cell.py BidirectionalCell.unroll —
            # SequenceReverse on inputs)
            merged_in = _merge(seq, 0)  # (T,N,C)
            rev_in = nd.SequenceReverse(merged_in, valid_length,
                                        use_sequence_length=True,
                                        axis=0)
            rev = _split_steps(rev_in, len(seq), 0)
        r_out, r_states = r_cell.unroll(
            length, rev, states[n_l:], layout=step_layout,
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_out = list(reversed(r_out))
        else:
            merged_r = _merge(r_out, 0)  # (T,N,H)
            merged_r = nd.SequenceReverse(merged_r, valid_length,
                                          use_sequence_length=True,
                                          axis=0)
            r_out = _split_steps(merged_r, len(seq), 0)
        outputs = [nd.concat(l, r, dim=-1)
                   for l, r in zip(l_out, r_out)]
        if merge_outputs is None or merge_outputs:
            outputs = _merge(outputs, axis)
        return outputs, l_states + r_states
