"""Fused Gluon RNN layers (ref: python/mxnet/gluon/rnn/rnn_layer.py —
RNN/LSTM/GRU wrapping the fused `RNN` op, which there dispatched to
cuDNN and here lowers to a lax.scan kernel, ops/rnn.py).

Parameters are kept unfused per layer/direction
({l,r}{i}_i2h_weight...) exactly like the reference, and concatenated
into the op's flat vector at forward — so checkpoints interop with the
cell-based API."""

from ...ndarray.ndarray import NDArray
from ...ops.rnn import _GATES
from ..block import HybridBlock
from .rnn_cell import (RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
                       BidirectionalCell)

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """Base fused layer (ref: rnn_layer.py _RNNLayer)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"invalid layout {layout}; must be TNC or NTC"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        G = _GATES[mode]
        ng, ni, nh = G, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][:self._dir]:
                    self._register_param(
                        f"{j}{i}_i2h_weight", (ng * nh, ni),
                        i2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_weight", (ng * nh, nh),
                        h2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_i2h_bias", (ng * nh,),
                        i2h_bias_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_bias", (ng * nh,),
                        h2h_bias_initializer)
                ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def shape_from_input(self, x):
        ni = x.shape[-1]
        G = _GATES[self._mode]
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, f"{j}{i}_i2h_weight").shape = \
                    (G * self._hidden_size, ni)
            ni = self._hidden_size * self._dir

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import nd
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **info, **kwargs))
        return states

    def _flat_params(self, params):
        """Concatenate per-layer params into the op's packed vector
        (cuDNN order: all weights layer-major, then all biases)."""
        from ... import nd
        chunks = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                chunks.append(params[f"{j}{i}_i2h_weight"].reshape(-1))
                chunks.append(params[f"{j}{i}_h2h_weight"].reshape(-1))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                chunks.append(params[f"{j}{i}_i2h_bias"])
                chunks.append(params[f"{j}{i}_h2h_bias"])
        return nd.concat(*chunks, dim=0)

    def forward(self, inputs, states=None):
        params = self._materialized_params([inputs])
        from ... import nd as F
        return self.hybrid_forward(F, inputs, states, **params)

    def __call__(self, inputs, states=None):
        return self.forward(inputs, states)

    def hybrid_forward(self, F, inputs, states=None, **params):
        batch_axis = self._layout.find("N")
        batch_size = inputs.shape[batch_axis]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size,
                                      ctx=getattr(inputs, "context",
                                                  None))
        if isinstance(states, NDArray):
            states = [states]
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        flat = self._flat_params(params)
        out = F.RNN(inputs, flat, *states,
                    state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True)
        outputs, out_states = out[0], list(out[1:])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if skip_states:
            return outputs
        return outputs, out_states

    def _unfuse(self):
        """Equivalent stack of unfused cells (ref: rnn_layer.py
        _unfuse) — shares this layer's parameters."""
        get_cell = {
            "rnn_relu": lambda **kw: RNNCell(self._hidden_size,
                                             activation="relu", **kw),
            "rnn_tanh": lambda **kw: RNNCell(self._hidden_size,
                                             activation="tanh", **kw),
            "lstm": lambda **kw: LSTMCell(self._hidden_size, **kw),
            "gru": lambda **kw: GRUCell(self._hidden_size, **kw),
        }[self._mode]
        stack = SequentialRNNCell(prefix=self.prefix,
                                  params=self.params)
        with stack.name_scope():
            ni = self._input_size
            for i in range(self._num_layers):
                kwargs = {
                    "input_size": ni,
                    "i2h_weight_initializer":
                        self._i2h_weight_initializer,
                    "h2h_weight_initializer":
                        self._h2h_weight_initializer,
                    "i2h_bias_initializer":
                        self._i2h_bias_initializer,
                    "h2h_bias_initializer":
                        self._h2h_bias_initializer,
                }
                if self._dir == 2:
                    stack.add(BidirectionalCell(
                        get_cell(prefix=f"l{i}_", **kwargs),
                        get_cell(prefix=f"r{i}_", **kwargs)))
                else:
                    stack.add(get_cell(prefix=f"l{i}_", **kwargs))
                ni = self._hidden_size * self._dir
        return stack


class RNN(_RNNLayer):
    """Vanilla multi-layer RNN (ref: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer,
                         h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Fused multi-layer LSTM (ref: rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC",
                 dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer,
                         h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Fused multi-layer GRU (ref: rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC",
                 dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer,
                         h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
