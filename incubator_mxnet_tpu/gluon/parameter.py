"""Gluon Parameter / ParameterDict
(ref: python/mxnet/gluon/parameter.py — Parameter:43 lazy init +
per-ctx copies, _reduce:246, ParameterDict:419).

TPU-native note: the reference keeps one copy of each parameter per
GPU context; under XLA a parameter is a single (possibly sharded)
jax.Array, so `list_data()` returns the one array and sharding is
expressed with `jax.sharding` annotations instead of copies (see
parallel package).
"""
import numpy as np

from .. import autograd
from .. import initializer as init_mod
from ..base import np_dtype
from ..context import default_context
from ..initializer import InitDesc
from ..ndarray import zeros as nd_zeros
from ..ndarray.ndarray import NDArray
from ..symbol.symbol import Variable

__all__ = ["Parameter", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(RuntimeError):
    """Parameter accessed before its shape was known."""


class Parameter:
    """A weight/state tensor of a Block (ref: parameter.py:43)."""

    def __init__(self, name, grad_req="write", shape=None, dtype=None,
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = np_dtype(dtype) if dtype is not None else \
            np.dtype("float32")
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self.grad_req = grad_req
        self._data = None
        self._grad = None
        self._deferred_init = None
        self._ctx = None

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self.shape}, "
                f"dtype={self.dtype})")

    # ------------------------------------------------------------ init
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        default_init = default_init or init_mod.Uniform(0.07)
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else None
        self._ctx = ctx or default_context()
        if self.shape is None or any(s == 0 for s in self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, default_init)
                return
            raise ValueError(
                f"cannot initialize parameter {self.name} with "
                f"unknown shape {self.shape}")
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        data = nd_zeros(self.shape, ctx=self._ctx, dtype=self.dtype)
        initializer = init or self.init or default_init
        initializer = init_mod.create(initializer) \
            if isinstance(initializer, str) else initializer
        initializer(InitDesc(self.name), data)
        self._set_data_arr(data)
        self._deferred_init = None

    def _set_data_arr(self, data):
        self._data = data
        if self.grad_req != "null":
            self._grad = nd_zeros(data.shape, ctx=self._ctx,
                                  dtype=data.dtype)
            autograd.mark_variables([self._data], [self._grad],
                                    self.grad_req)
        else:
            self._grad = None

    def _finish_deferred_init(self, shape):
        """Called by layers once the input shape reveals ours."""
        self.shape = tuple(shape)
        if self._deferred_init is not None:
            init, default_init = self._deferred_init
            self._finish_init(init, default_init)

    def _shape_known(self):
        return self.shape is not None and all(s != 0 for s in self.shape)

    # ------------------------------------------------------------ access
    def data(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"parameter {self.name} deferred-initialized; run a "
                    "forward pass (or set shape) first")
            raise RuntimeError(
                f"parameter {self.name} not initialized; call "
                ".initialize()")
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        if self.grad_req == "null":
            raise RuntimeError(f"parameter {self.name} has grad_req="
                               "'null'")
        if self._grad is None:
            raise RuntimeError(f"parameter {self.name} not initialized")
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self._ctx or default_context()]

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0

    def set_data(self, data):
        if self._data is None:
            self.shape = tuple(data.shape)
            self._ctx = self._ctx or default_context()
            self._set_data_arr(data if isinstance(data, NDArray)
                               else NDArray(data))
        else:
            self._data[:] = data

    def reset_ctx(self, ctx):
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        self._ctx = ctx
        if self._data is not None:
            self._data._data = self._data.as_in_context(ctx)._data

    def cast(self, dtype):
        self.dtype = np_dtype(dtype)
        if self._data is not None:
            self._set_data_arr(self._data.astype(dtype))

    def var(self):
        """Symbol variable for this parameter (ref: parameter.py var)."""
        return Variable(self.name, lr_mult=self.lr_mult,
                        wd_mult=self.wd_mult)


class ParameterDict:
    """Ordered name->Parameter mapping with prefix + shared fallback
    (ref: parameter.py:419)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        return f"ParameterDict({self._prefix}: {list(self._params)})"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        """Get or create a parameter named prefix+name."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    if param.shape is None or \
                            any(s == 0 for s in param.shape):
                        param.shape = tuple(v)
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None:
            p = self._shared._get_impl(name)
            if p is not None:
                self._params[name] = p
            return p
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"duplicate parameter {k}")
            self._params[k] = v

    # ------------------------------------------------------------ bulk ops
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        init = init or init_mod.Uniform(0.07)
        for p in self.values():
            p.initialize(None, ctx, init, force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    # ------------------------------------------------------------ io
    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save
        arg = {}
        for p in self.values():
            if p._data is None:
                continue
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = p.data()
        nd_save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import load as nd_load
        from ..resilience import CheckpointCorruptError
        try:
            loaded = nd_load(filename)
        except CheckpointCorruptError as exc:
            # parameter files carry no epoch numbering, so there is
            # nothing to fall back to — fail with provenance instead
            # of half-applying a torn file
            raise CheckpointCorruptError(
                f"cannot load parameters from {filename}: {exc}"
            ) from exc
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise IOError(f"parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise IOError(f"extra parameters in file: {extra}")
