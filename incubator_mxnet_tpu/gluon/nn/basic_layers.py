"""Gluon basic layers (ref: python/mxnet/gluon/nn/basic_layers.py —
Sequential, HybridSequential, Dense, Activation, Dropout, BatchNorm,
LeakyReLU, Embedding, Flatten, Lambda, HybridLambda; plus
InstanceNorm/LayerNorm from later reference versions)."""
import numpy as np

from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "Dropout", "BatchNorm", "LeakyReLU", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "InstanceNorm", "LayerNorm"]


class Sequential(Block):
    """Sequential container (ref: basic_layers.py Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._layers = []

    def add(self, *blocks):
        for block in blocks:
            self._layers.append(block)
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]

    def __iter__(self):
        return iter(self._children)


class HybridSequential(HybridBlock):
    """Hybridizable sequential container."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def hybrid_forward(self, F, x):
        for block in self._children:
            x = block(x)
        return x

    def shape_from_input(self, *inputs):
        pass  # children handle their own deferred shapes

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]

    def __iter__(self):
        return iter(self._children)


class Dense(HybridBlock):
    """Fully-connected layer (ref: basic_layers.py Dense)."""

    def __init__(self, units, activation=None, use_bias=True,
                 flatten=True, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._use_bias = use_bias
        self._activation = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer)

    def shape_from_input(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten \
            else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        if self.weight._deferred_init is not None or \
                not self.weight._shape_known():
            self.shape_from_input(x)
            self.weight._finish_deferred_init(self.weight.shape)
            weight = self.weight.data()
        out = F.FullyConnected(x, weight, bias,
                               num_hidden=self._units,
                               no_bias=not self._use_bias,
                               flatten=self._flatten)
        if self._activation is not None:
            out = F.Activation(out, act_type=self._activation)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def _alias(self):
        return self._act_type if hasattr(self, "_act_type") \
            else "activation"

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """(ref: basic_layers.py BatchNorm) running stats are grad_req=null
    parameters; the hybrid cache returns their updated values."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,),
                init=gamma_initializer, allow_deferred_init=True,
                differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,),
                init=beta_initializer, allow_deferred_init=True,
                differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer,
                allow_deferred_init=True, differentiable=False)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer,
                allow_deferred_init=True, differentiable=False)

    def shape_from_input(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean,
                       running_var):
        if not self.gamma._shape_known():
            self.shape_from_input(x)
            for p in (self.gamma, self.beta, self.running_mean,
                      self.running_var):
                p._finish_deferred_init(p.shape)
            gamma, beta = self.gamma.data(), self.beta.data()
            running_mean = self.running_mean.data()
            running_var = self.running_var.data()
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           eps=self._epsilon, momentum=self._momentum,
                           fix_gamma=not self._scale,
                           use_global_stats=self._use_global_stats,
                           axis=self._axis)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype)

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Lambda(Block):
    """(ref: basic_layers.py Lambda)"""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import nd as nd_mod
            function = getattr(nd_mod, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else \
            function.__name__
        self._func = function

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, *args):
        if isinstance(self._func, str):
            return getattr(F, self._func)(*args)
        return self._func(F, *args)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def shape_from_input(self, x):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        if not self.gamma._shape_known():
            self.shape_from_input(x)
            for p in (self.gamma, self.beta):
                p._finish_deferred_init(p.shape)
            gamma, beta = self.gamma.data(), self.beta.data()
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def shape_from_input(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        if not self.gamma._shape_known():
            self.shape_from_input(x)
            for p in (self.gamma, self.beta):
                p._finish_deferred_init(p.shape)
            gamma, beta = self.gamma.data(), self.beta.data()
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)
