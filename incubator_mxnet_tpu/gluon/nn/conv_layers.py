"""Gluon conv/pool layers (ref: python/mxnet/gluon/nn/conv_layers.py —
Conv1-3D, Conv1-3DTranspose, Max/AvgPool1-3D, GlobalMax/AvgPool1-3D).
"""
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose", "MaxPool1D",
           "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
           "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
           "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D",
           "GlobalAvgPool3D"]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding,
                 dilation, groups, layout, in_channels=0,
                 activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 ndim=2, transpose=False, output_padding=0, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _tup(kernel_size, ndim)
        self._strides = _tup(strides, ndim)
        self._padding = _tup(padding, ndim)
        self._dilation = _tup(dilation, ndim)
        self._groups = groups
        self._ndim = ndim
        self._activation = activation
        self._use_bias = use_bias
        self._transpose = transpose
        self._output_padding = _tup(output_padding, ndim)
        with self.name_scope():
            if transpose:
                wshape = (in_channels, channels // groups) + self._kernel
            else:
                wshape = (channels, in_channels // max(groups, 1)
                          if in_channels else 0) + self._kernel
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer)

    def shape_from_input(self, x):
        c = x.shape[1]
        if self._transpose:
            self.weight.shape = (c, self._channels // self._groups) \
                + self._kernel
        else:
            self.weight.shape = (self._channels, c // self._groups) \
                + self._kernel

    def hybrid_forward(self, F, x, weight, bias=None):
        if not self.weight._shape_known():
            self.shape_from_input(x)
            self.weight._finish_deferred_init(self.weight.shape)
            weight = self.weight.data()
        if self._transpose:
            out = F.Deconvolution(
                x, weight, bias, kernel=self._kernel,
                stride=self._strides, pad=self._padding,
                dilate=self._dilation, adj=self._output_padding,
                num_filter=self._channels, num_group=self._groups,
                no_bias=not self._use_bias)
        else:
            out = F.Convolution(
                x, weight, bias, kernel=self._kernel,
                stride=self._strides, pad=self._padding,
                dilate=self._dilation, num_filter=self._channels,
                num_group=self._groups, no_bias=not self._use_bias)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out


def _make_conv(name, ndim, transpose):
    class _C(_Conv):
        def __init__(self, channels, kernel_size, strides=1, padding=0,
                     dilation=1, groups=1, layout=None,
                     output_padding=0, activation=None, use_bias=True,
                     weight_initializer=None, bias_initializer="zeros",
                     in_channels=0, **kwargs):
            super().__init__(channels, kernel_size, strides, padding,
                             dilation, groups, layout, in_channels,
                             activation, use_bias, weight_initializer,
                             bias_initializer, ndim=ndim,
                             transpose=transpose,
                             output_padding=output_padding, **kwargs)
    _C.__name__ = name
    _C.__qualname__ = name
    _C.__doc__ = f"{name} layer (ref: gluon/nn/conv_layers.py)."
    return _C


Conv1D = _make_conv("Conv1D", 1, False)
Conv2D = _make_conv("Conv2D", 2, False)
Conv3D = _make_conv("Conv3D", 3, False)
Conv1DTranspose = _make_conv("Conv1DTranspose", 1, True)
Conv2DTranspose = _make_conv("Conv2DTranspose", 2, True)
Conv3DTranspose = _make_conv("Conv3DTranspose", 3, True)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode,
                 global_pool, pool_type, ndim, **kwargs):
        super().__init__(**kwargs)
        self._kernel = _tup(pool_size, ndim)
        self._stride = _tup(strides if strides is not None
                            else pool_size, ndim)
        self._pad = _tup(padding, ndim)
        self._global = global_pool
        self._pool_type = pool_type
        self._convention = "full" if ceil_mode else "valid"

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, x):
        return F.Pooling(x, kernel=self._kernel, stride=self._stride,
                         pad=self._pad, pool_type=self._pool_type,
                         global_pool=self._global,
                         pooling_convention=self._convention)


def _make_pool(name, ndim, pool_type, global_pool):
    if global_pool:
        class _P(_Pool):
            def __init__(self, layout=None, **kwargs):
                super().__init__(1, 1, 0, False, True, pool_type, ndim,
                                 **kwargs)
    else:
        class _P(_Pool):
            def __init__(self, pool_size=2, strides=None, padding=0,
                         layout=None, ceil_mode=False, **kwargs):
                super().__init__(pool_size, strides, padding, ceil_mode,
                                 False, pool_type, ndim, **kwargs)
    _P.__name__ = name
    _P.__qualname__ = name
    _P.__doc__ = f"{name} (ref: gluon/nn/conv_layers.py)."
    return _P


MaxPool1D = _make_pool("MaxPool1D", 1, "max", False)
MaxPool2D = _make_pool("MaxPool2D", 2, "max", False)
MaxPool3D = _make_pool("MaxPool3D", 3, "max", False)
AvgPool1D = _make_pool("AvgPool1D", 1, "avg", False)
AvgPool2D = _make_pool("AvgPool2D", 2, "avg", False)
AvgPool3D = _make_pool("AvgPool3D", 3, "avg", False)
GlobalMaxPool1D = _make_pool("GlobalMaxPool1D", 1, "max", True)
GlobalMaxPool2D = _make_pool("GlobalMaxPool2D", 2, "max", True)
GlobalMaxPool3D = _make_pool("GlobalMaxPool3D", 3, "max", True)
GlobalAvgPool1D = _make_pool("GlobalAvgPool1D", 1, "avg", True)
GlobalAvgPool2D = _make_pool("GlobalAvgPool2D", 2, "avg", True)
GlobalAvgPool3D = _make_pool("GlobalAvgPool3D", 3, "avg", True)
