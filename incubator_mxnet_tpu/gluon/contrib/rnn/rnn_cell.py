"""Contrib recurrent cells (ref:
python/mxnet/gluon/contrib/rnn/rnn_cell.py — VariationalDropoutCell).
"""
from ...rnn.rnn_cell import (BidirectionalCell, ModifierCell,
                             SequentialRNNCell)

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational (locked) dropout: ONE mask per sequence, shared
    across time steps, separately for inputs / states / outputs
    (Gal & Ghahramani 2016).  Masks are drawn on the first step and
    persist until ``reset()`` — call it between sequences when
    stepping manually (``unroll`` resets automatically)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        if drop_states and isinstance(base_cell, BidirectionalCell):
            raise ValueError(
                "BidirectionalCell doesn't support variational state "
                "dropout; wrap the inner cells instead")
        if drop_states and isinstance(base_cell, SequentialRNNCell):
            raise ValueError(
                "wrap the cells inside the SequentialRNNCell "
                "individually for variational state dropout")
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._masks = {}

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._masks = {}

    def _mask(self, key, p, like):
        from .... import nd
        if key not in self._masks:
            self._masks[key] = nd.Dropout(nd.ones_like(like), p=p)
        return self._masks[key]

    def __call__(self, inputs, states):
        from .... import autograd
        if autograd.is_training():
            if self.drop_inputs:
                inputs = inputs * self._mask(
                    "in", self.drop_inputs, inputs)
            if self.drop_states:
                states = [states[0] * self._mask(
                    "st", self.drop_states, states[0])] + \
                    list(states[1:])
        output, next_states = self.base_cell(inputs, states)
        if autograd.is_training() and self.drop_outputs:
            output = output * self._mask(
                "out", self.drop_outputs, output)
        return output, next_states

    # no unroll override needed: RecurrentCell.unroll calls
    # self.reset() first, which redraws the locked masks per sequence
