"""Convolutional recurrent cells (ref:
python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py — Conv{1,2,3}D
{RNN,LSTM,GRU}Cell).

One generic base drives all nine cells: the input-to-hidden and
hidden-to-hidden projections are N-D convolutions (both lower to
``lax.conv_general_dilated`` — the MXU path), with the h2h conv
'same'-padded so the recurrent state keeps its spatial shape.  As in
the reference, ``input_shape`` (C, *spatial) is declared up front so
state shapes are static — which also keeps the unrolled scan fully
shape-static under jit.
"""
from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, n, name):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) != n:
        raise ValueError(f"{name} must have {n} dims, got {v}")
    return v


def _conv_out(size, kernel, pad, dilate):
    return tuple(
        (s + 2 * p - d * (k - 1) - 1) + 1
        for s, k, p, d in zip(size, kernel, pad, dilate))


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared conv/param plumbing (ref: conv_rnn_cell.py
    _BaseConvRNNCell:37)."""

    _gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 activation="tanh", conv_dims=2,
                 i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        n = conv_dims
        self._nd = n
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        if len(self._input_shape) != n + 1:
            raise ValueError(
                f"input_shape needs {n + 1} dims (C, *spatial), got "
                f"{self._input_shape}")
        self._hidden_channels = hidden_channels
        self._i2h_kernel = _tup(i2h_kernel, n, "i2h_kernel")
        self._h2h_kernel = _tup(h2h_kernel, n, "h2h_kernel")
        if any(k % 2 == 0 for k in self._h2h_kernel):
            raise ValueError(
                f"h2h_kernel must be odd in every dim (got "
                f"{self._h2h_kernel}) so 'same' padding preserves "
                "the state's spatial shape")
        self._i2h_pad = _tup(i2h_pad, n, "i2h_pad")
        self._i2h_dilate = _tup(i2h_dilate, n, "i2h_dilate")
        self._h2h_dilate = _tup(h2h_dilate, n, "h2h_dilate")
        self._h2h_pad = tuple(
            d * (k - 1) // 2
            for k, d in zip(self._h2h_kernel, self._h2h_dilate))
        self._activation = activation

        in_c, in_spatial = self._input_shape[0], self._input_shape[1:]
        self._state_spatial = _conv_out(
            in_spatial, self._i2h_kernel, self._i2h_pad,
            self._i2h_dilate)
        G = self._gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(G * hidden_channels, in_c) + self._i2h_kernel,
                init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(G * hidden_channels,
                       hidden_channels) + self._h2h_kernel,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(G * hidden_channels,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(G * hidden_channels,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + \
            self._state_spatial
        if self._gates == 4:            # LSTM: h and c
            return [{"shape": shape, "__layout__": "NC" + "DHW"
                     [3 - self._nd:]}] * 2
        return [{"shape": shape,
                 "__layout__": "NC" + "DHW"[3 - self._nd:]}]

    def _convs(self, F, inputs, state):
        G = self._gates
        i2h = F.Convolution(
            inputs, self.i2h_weight.data(), self.i2h_bias.data(),
            kernel=self._i2h_kernel, pad=self._i2h_pad,
            dilate=self._i2h_dilate,
            num_filter=G * self._hidden_channels)
        h2h = F.Convolution(
            state, self.h2h_weight.data(), self.h2h_bias.data(),
            kernel=self._h2h_kernel, pad=self._h2h_pad,
            dilate=self._h2h_dilate,
            num_filter=G * self._hidden_channels)
        return i2h, h2h

    def _act(self, F, x):
        return F.Activation(x, act_type=self._activation)


class _ConvRNNCell(_BaseConvRNNCell):
    _gates = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, **_):
        i2h, h2h = self._convs(F, inputs, states[0])
        out = self._act(F, i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _gates = 4

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, **_):
        i2h, h2h = self._convs(F, inputs, states[0])
        gates = i2h + h2h
        g = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(g[0], act_type="sigmoid")
        f = F.Activation(g[1], act_type="sigmoid")
        c_in = self._act(F, g[2])
        o = F.Activation(g[3], act_type="sigmoid")
        next_c = f * states[1] + i * c_in
        next_h = o * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _gates = 3

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, **_):
        prev = states[0]
        i2h, h2h = self._convs(F, inputs, prev)
        ig = F.SliceChannel(i2h, num_outputs=3, axis=1)
        hg = F.SliceChannel(h2h, num_outputs=3, axis=1)
        r = F.Activation(ig[0] + hg[0], act_type="sigmoid")
        z = F.Activation(ig[1] + hg[1], act_type="sigmoid")
        n = self._act(F, ig[2] + r * hg[2])
        next_h = (1.0 - z) * n + z * prev
        return next_h, [next_h]


def _specialize(base, dims, name):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, **kwargs):
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, conv_dims=dims, **kwargs)

    cls = type(name, (base,), {"__init__": __init__, "__doc__":
                               f"{dims}-D {base.__doc__ or name} "
                               f"(ref: conv_rnn_cell.py {name})"})
    return cls


Conv1DRNNCell = _specialize(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _specialize(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _specialize(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _specialize(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _specialize(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _specialize(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _specialize(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _specialize(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _specialize(_ConvGRUCell, 3, "Conv3DGRUCell")
