"""Contrib gluon APIs (ref: python/mxnet/gluon/contrib/)."""
from . import rnn

__all__ = ["rnn"]
