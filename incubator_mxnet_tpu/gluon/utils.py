"""Gluon utilities (ref: python/mxnet/gluon/utils.py —
split_data, split_and_load, clip_global_norm)."""
import math

from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """(ref: utils.py split_data)"""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(f"batch {size} too small for {num_slice} slices")
    if even_split and size % num_slice != 0:
        raise ValueError(f"batch {size} not divisible by {num_slice}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = size if i == num_slice - 1 else (i + 1) * step
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Slice a batch across contexts (ref: utils.py split_and_load).
    On a sharded mesh prefer parallel.shard_batch which annotates one
    global array instead of materializing slices."""
    from ..ndarray import array as nd_array
    if not isinstance(data, NDArray):
        data = nd_array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """(ref: utils.py clip_global_norm)

    Returns the computed global norm.  Non-finite-safe: when the
    norm is NaN/Inf (one bad gradient) and ``check_isfinite``, the
    arrays are left untouched and a warning is raised — scaling by a
    non-finite factor would turn EVERY gradient to NaN, converting
    one bad array into a fully poisoned step.  Callers should test
    ``math.isfinite(norm)`` and skip the update (or let the step
    sentinel do it — docs/numeric_stability.md)."""
    import warnings
    total = 0.0
    for a in arrays:
        n = a.norm().asscalar()
        total += float(n) ** 2
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        warnings.warn(
            f"clip_global_norm: non-finite total norm ({total}); "
            "arrays left unscaled — check the norm and skip this "
            "update", RuntimeWarning)
        return total
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total
