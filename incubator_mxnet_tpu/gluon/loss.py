"""Gluon losses (ref: python/mxnet/gluon/loss.py — Loss:66 L2Loss:100
L1Loss:138 SigmoidBinaryCrossEntropyLoss:176
SoftmaxCrossEntropyLoss:242 KLDivLoss:324 CTCLoss:398 HuberLoss:478
HingeLoss:527 SquaredHingeLoss:571 LogisticLoss:615 TripletLoss:656).
"""
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """(ref: loss.py _apply_weighting)"""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """Base loss (ref: loss.py:66)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def shape_from_input(self, *inputs):
        pass

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


def _mean_all_but_batch(F, loss, batch_axis):
    return F.mean(loss, axis=batch_axis, exclude=True)


class L2Loss(Loss):
    """0.5*(pred-label)^2 (ref: loss.py:100)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(pred - label)
        loss = _apply_weighting(F, loss, self._weight / 2,
                                sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """(ref: loss.py:176)"""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # numerically stable: max(x,0) - x*z + log(1+exp(-|x|))
            loss = F.relu(pred) - pred * label + \
                F.Activation(-F.abs(pred), act_type="softrelu")
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label
                     + F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """(ref: loss.py:242)"""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """(ref: loss.py:324)"""

    def __init__(self, from_logits=True, axis=-1, weight=None,
                 batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class CTCLoss(Loss):
    """Connectionist temporal classification (ref: loss.py:398;
    kernel: ops/contrib ctc_loss, replaces warp-ctc)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)
        if self._label_layout == "TN":
            label = F.swapaxes(label, dim1=0, dim2=1)
        args = [pred, label]
        # gluon convention (ref: gluon/loss.py:439-446): labels are
        # classes 0..C-2 padded with -1, blank is the LAST channel
        kw = {"blank_label": "last"}
        if pred_lengths is not None:
            kw["use_data_lengths"] = True
            args.append(pred_lengths)
        if label_lengths is not None:
            kw["use_label_lengths"] = True
            args.append(label_lengths)
        loss = F.ctc_loss(*args, **kw)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    """(ref: loss.py:478)"""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class HingeLoss(Loss):
    """(ref: loss.py:527)"""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    """(ref: loss.py:571)"""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class LogisticLoss(Loss):
    """(ref: loss.py:615)"""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class TripletLoss(Loss):
    """(ref: loss.py:656)"""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(pred - positive)
                     - F.square(pred - negative),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)
