"""Gluon Block / HybridBlock (ref: python/mxnet/gluon/block.py —
Block:121, HybridBlock:306, _build_cache:365, hybridize:428;
C++ CachedOp ref: src/imperative/cached_op.cc).

TPU-native hybridize: instead of building an nnvm graph and replaying
engine pushes, `hybridize()` wraps the block's forward in `jax.jit`.
The trace runs the exact same NDArray code with tracers inside;
XLA compiles the whole block (fusion + memory planning), and the
shape/dtype-keyed jit cache plays the role of CachedOp's signature
cache (cached_op.cc:171).  Gradients flow by recording one tape node
whose vjp is the jitted function's vjp.  Aux-state (BatchNorm moving
stats) round-trips functionally: param values go in, updated values
for grad_req='null' params come out and are written back.
"""
import re
import threading

import jax

from .. import autograd, random_state
from ..autograd import TapeNode
from ..ndarray.ndarray import NDArray
from ..symbol.symbol import Symbol
from .parameter import (DeferredInitializationError,
                        ParameterDict)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name scoping for nested blocks (ref: block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    _global_counter = {}

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                count = _BlockScope._global_counter.get(hint, 0)
                _BlockScope._global_counter[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._block.params
            # inherit the parent's shared fallback so cells created
            # under a scope with shared params resolve into it
            # (ref: block.py _BlockScope.create)
            params = ParameterDict(parent.prefix + prefix,
                                   shared=parent._shared)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        _BlockScope._current.value = self._old_scope


class Block:
    """Base building block (ref: gluon/block.py Block:121)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All parameters of self + descendants
        (ref: block.py collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self.params.items()
                        if pattern.match(k)})
        for child in self._children:
            ret.update(child.collect_params(select))
        return ret

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = getattr(self, name, None)
            if isinstance(existing, Block):
                self._children[self._children.index(existing)] = value
            else:
                self._children.append(value)
        super().__setattr__(name, value)

    def register_child(self, block):
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose,
                                         force_reinit)

    def cast(self, dtype):
        for child in self._children:
            child.cast(dtype)
        for _, p in self.params.items():
            p.cast(dtype)

    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing,
                                   ignore_extra,
                                   restore_prefix=self.prefix)

    save_parameters = save_params
    load_parameters = load_params

    def hybridize(self, active=True):
        for child in self._children:
            child.hybridize(active)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError

    def __repr__(self):
        return f"{self.__class__.__name__}({self._name})"


class HybridBlock(Block):
    """Block compilable into one XLA executable
    (ref: block.py HybridBlock:306)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_fn = None
        self._param_order = None

    def hybridize(self, active=True):
        self._active = active
        self._cached_fn = None
        super().hybridize(active)

    def cast(self, dtype):
        self._cached_fn = None
        super().cast(dtype)

    def infer_shape(self, *args):
        """Run a shape-only pass to finish deferred param init."""
        self._deferred_infer(args)

    def _deferred_infer(self, args):
        # eager probe with eval_shape would need materialized params;
        # layers override _pre_infer via their forward needing only
        # shapes.  Default: run eagerly once params allow it.
        pass

    # ------------------------------------------------------------ call
    def __call__(self, *args):
        if any(isinstance(a, Symbol) for a in args):
            # export tracing: children build graph nodes
            return self._to_symbol(*args)
        self._in_arity = len(args)
        if not self._active:
            return self.forward(*args)
        # inside an enclosing cache trace, inputs are tracers: run the
        # Python body directly — the outer jit already compiles us
        for a in args:
            if isinstance(a, NDArray) and isinstance(a._data,
                                                     jax.core.Tracer):
                return self.forward(*args)
        return self._call_cached(*args)

    # ------------------------------------------------------------ export
    def _to_symbol(self, *sym_inputs):
        """Trace this block into a Symbol graph: own parameters become
        named Variables, ops build graph nodes because every layer's
        hybrid_forward goes through F (here the symbol frontend)."""
        from .. import symbol as sym_mod
        params = {self._strip(name): sym_mod.Variable(name)
                  for name, p in self.params.items()}
        return self.hybrid_forward(sym_mod, *sym_inputs, **params)

    def export(self, path, epoch=0):
        """Export the block as symbol JSON + params servable by
        ``symbol.load`` + Executor / ``Predictor`` / ``Module.load``
        (ref: python/mxnet/gluon/block.py HybridBlock.export).

        Writes ``path-symbol.json`` and ``path-%04d.params``.  The
        block must have run forward at least once (shapes settled).
        """
        from .. import symbol as sym_mod
        from ..model import save_checkpoint
        n = getattr(self, "_in_arity", 1)
        names = ["data"] if n == 1 else [f"data{i}" for i in range(n)]
        out = self._to_symbol(*[sym_mod.Variable(nm) for nm in names])
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(out)
        aux_names = set(out.list_auxiliary_states())
        arg, aux = {}, {}
        for name, p in self.collect_params().items():
            (aux if name in aux_names else arg)[name] = p.data()
        save_checkpoint(path, epoch, out, arg, aux)
        return out

    def forward(self, *args):
        """Eager path: hybrid_forward with nd + concrete params."""
        from .. import nd as nd_mod
        params = self._materialized_params(args)
        return self.hybrid_forward(nd_mod, *args, **params)

    def _materialized_params(self, args):
        try:
            return {self._strip(name): p.data()
                    for name, p in self.params.items()}
        except DeferredInitializationError:
            self._finish_deferred(args)
            return {self._strip(name): p.data()
                    for name, p in self.params.items()}

    def _strip(self, name):
        # strip the parameter DICT's prefix, not the block's: a block
        # built with params=other.params shares the donor dict and
        # with it the donor's prefix (weight tying, ref: gluon
        # word_language_model model.py tie_weights) — its param
        # names carry the donor prefix while self.prefix differs
        pfx = self.params.prefix
        if name.startswith(pfx):
            return name[len(pfx):]
        return name[len(self.prefix):] if \
            name.startswith(self.prefix) else name

    def _finish_deferred(self, args):
        """Infer deferred shapes from input shapes via layer hook."""
        self.shape_from_input(*[a for a in args
                                if isinstance(a, NDArray)])
        for _, p in self.params.items():
            if p._deferred_init is not None and p._shape_known():
                p._finish_deferred_init(p.shape)

    def shape_from_input(self, *inputs):
        """Layers with deferred params override to set shapes."""
        raise DeferredInitializationError(
            f"{self.name}: parameter shapes unknown; construct with "
            "explicit in_units/in_channels or run initialize() after "
            "setting shapes")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------ cached
    def _build_cache(self):
        """Create the jitted callable (ref: block.py _build_cache:365)."""
        params = self.collect_params()
        # stable ordering for the pytree
        names = sorted(params.keys())
        param_objs = [params[n] for n in names]
        trainable_idx = [i for i, p in enumerate(param_objs)
                         if p.grad_req != "null"]
        state_idx = [i for i, p in enumerate(param_objs)
                     if p.grad_req == "null"]
        block = self

        def run(param_vals, input_vals, rng, training):
            saved = [(p, p._data._data) for p in param_objs]
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(training)
            try:
                for p, v in zip(param_objs, param_vals):
                    p._data._data = v
                with random_state.key_provider(rng):
                    outs = block.forward(
                        *[NDArray(v) for v in input_vals])
                out_list = outs if isinstance(outs, (list, tuple)) \
                    else [outs]
                out_vals = [o._data for o in out_list]
                state_vals = [param_objs[i]._data._data
                              for i in state_idx]
            finally:
                for (p, v) in saved:
                    p._data._data = v
                autograd.set_recording(prev_rec)
                autograd.set_training(prev_train)
            return out_vals, state_vals

        def fwd(param_vals, input_vals, rng, training):
            return run(list(param_vals), list(input_vals), rng, training)

        jitted = jax.jit(fwd, static_argnums=(3,))
        return param_objs, trainable_idx, state_idx, jitted

    def _call_cached(self, *args):
        if self._cached_fn is None:
            # settle deferred shapes: one eager forward lets each layer
            # infer its own param shapes from its actual input (the
            # reference's deferred-init pass, ref: block.py
            # _deferred_infer_shape); then build the cache
            if any(p._deferred_init is not None
                   for _, p in self.collect_params().items()):
                with autograd.pause():
                    self.forward(*args)
            self._cached_fn = self._build_cache()
        param_objs, trainable_idx, state_idx, jitted = self._cached_fn
        param_vals = tuple(p.data()._data for p in param_objs)
        input_nds = [a for a in args if isinstance(a, NDArray)]
        input_vals = tuple(a._data for a in input_nds)
        rng = random_state.next_key()
        training = autograd.is_training()
        recording = autograd.is_recording()

        if recording:
            t_idx = trainable_idx

            def f(tvals, ivals):
                pvals = list(param_vals)
                for i, v in zip(t_idx, tvals):
                    pvals[i] = v
                return jitted(tuple(pvals), ivals, rng, training)

            (out_vals, state_vals), vjp_fn = jax.vjp(
                f, tuple(param_vals[i] for i in t_idx), input_vals)
        else:
            out_vals, state_vals = jitted(param_vals, input_vals, rng,
                                          training)

        if training:
            for i, v in zip(state_idx, state_vals):
                param_objs[i]._data._data = v

        out_arrays = [NDArray(v) for v in out_vals]
        if recording:
            import numpy as np

            def node_vjp(out_cts):
                cts = list(out_cts) if isinstance(out_cts, tuple) \
                    else [out_cts]
                state_cts = [
                    (np.zeros(v.shape, jax.dtypes.float0)
                     if not jax.numpy.issubdtype(v.dtype,
                                                 jax.numpy.floating)
                     else jax.numpy.zeros(v.shape, v.dtype))
                    for v in state_vals]
                tcts, icts = vjp_fn((cts, state_cts))
                return list(tcts) + list(icts)

            node_inputs = [param_objs[i]._data for i in trainable_idx] \
                + input_nds
            avals = [(tuple(v.shape), v.dtype) for v in out_vals]
            node = TapeNode(node_vjp, node_inputs, avals,
                            f"CachedOp({self.name})")
            for i, arr in enumerate(out_arrays):
                arr._autograd = (node, i)
        if len(out_arrays) == 1:
            return out_arrays[0]
        return out_arrays


class SymbolBlock(HybridBlock):
    """Wrap a Symbol + params as a Block (ref: block.py SymbolBlock)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=None)
        from ..symbol.symbol import Symbol, Group
        if isinstance(outputs, (list, tuple)):
            outputs = Group(outputs)
        self._symbol = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) \
            else [inputs]
        input_names = {i.name for i in self._inputs}
        for name in outputs.list_inputs():
            if name not in input_names:
                self._params.get(
                    name, allow_deferred_init=True, grad_req="write")
        if params is not None:
            for name, v in params.items():
                if name in self._params.keys():
                    self._params[name].set_data(v)

    def forward(self, *args):
        from ..executor import build_graph_fn
        arg_vals = {}
        for i, a in zip(self._inputs, args):
            arg_vals[i.name] = a._data
        for name, p in self.params.items():
            arg_vals[name] = p.data()._data
        run = build_graph_fn(self._symbol)
        outs, _ = run(arg_vals, {}, random_state.next_key(),
                      autograd.is_training())
        outs = [NDArray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError
