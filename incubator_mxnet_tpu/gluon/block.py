"""Gluon Block / HybridBlock (ref: python/mxnet/gluon/block.py —
Block:121, HybridBlock:306, _build_cache:365, hybridize:428;
C++ CachedOp ref: src/imperative/cached_op.cc).

TPU-native hybridize: instead of building an nnvm graph and replaying
engine pushes, `hybridize()` wraps the block's forward in `jax.jit`.
The trace runs the exact same NDArray code with tracers inside;
XLA compiles the whole block (fusion + memory planning), and the
shape/dtype-keyed jit cache plays the role of CachedOp's signature
cache (cached_op.cc:171).  Gradients flow by recording one tape node
whose vjp is the jitted function's vjp.  Aux-state (BatchNorm moving
stats) round-trips functionally: param values go in, updated values
for grad_req='null' params come out and are written back.
"""
import re
import threading

import jax

from .. import autograd, random_state
from ..ndarray.ndarray import NDArray
from ..symbol.symbol import Symbol
from .parameter import (DeferredInitializationError,
                        ParameterDict)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name scoping for nested blocks (ref: block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    _global_counter = {}

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                count = _BlockScope._global_counter.get(hint, 0)
                _BlockScope._global_counter[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._block.params
            # inherit the parent's shared fallback so cells created
            # under a scope with shared params resolve into it
            # (ref: block.py _BlockScope.create)
            params = ParameterDict(parent.prefix + prefix,
                                   shared=parent._shared)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        _BlockScope._current.value = self._old_scope


class Block:
    """Base building block (ref: gluon/block.py Block:121)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All parameters of self + descendants
        (ref: block.py collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self.params.items()
                        if pattern.match(k)})
        for child in self._children:
            ret.update(child.collect_params(select))
        return ret

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = getattr(self, name, None)
            if isinstance(existing, Block):
                self._children[self._children.index(existing)] = value
            else:
                self._children.append(value)
        super().__setattr__(name, value)

    def register_child(self, block):
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose,
                                         force_reinit)

    def cast(self, dtype):
        for child in self._children:
            child.cast(dtype)
        for _, p in self.params.items():
            p.cast(dtype)

    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing,
                                   ignore_extra,
                                   restore_prefix=self.prefix)

    save_parameters = save_params
    load_parameters = load_params

    def hybridize(self, active=True):
        for child in self._children:
            child.hybridize(active)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError

    def __repr__(self):
        return f"{self.__class__.__name__}({self._name})"


class HybridBlock(Block):
    """Block compilable into one XLA executable
    (ref: block.py HybridBlock:306).

    ``hybridize()`` swaps ``__call__`` onto a
    :class:`~..graph.cached_op.CachedOp`: the forward is traced once
    per (input shapes/dtypes, static args, train-flag) signature —
    through the graph-optimization pass pipeline when the block is
    symbol-traceable (``MXTPU_GRAPH_OPT`` >= 1), via ``jax.jit`` over
    the eager forward otherwise — and replayed as a compiled callable
    on every subsequent call.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_op = None
        self._cache_fallback = False

    def hybridize(self, active=True):
        self._active = active
        self._cached_op = None
        self._cache_fallback = False
        super().hybridize(active)

    def cast(self, dtype):
        self._cached_op = None
        super().cast(dtype)

    def infer_shape(self, *args):
        """Run a shape-only pass to finish deferred param init."""
        self._deferred_infer(args)

    def _deferred_infer(self, args):
        # eager probe with eval_shape would need materialized params;
        # layers override _pre_infer via their forward needing only
        # shapes.  Default: run eagerly once params allow it.
        pass

    # ------------------------------------------------------------ call
    def __call__(self, *args):
        if any(isinstance(a, Symbol) for a in args):
            # export tracing: children build graph nodes
            return self._to_symbol(*args)
        self._in_arity = len(args)
        if not self._active:
            return self.forward(*args)
        # inside an enclosing cache trace, inputs are tracers: run the
        # Python body directly — the outer jit already compiles us
        for a in args:
            if isinstance(a, NDArray) and isinstance(a._data,
                                                     jax.core.Tracer):
                return self.forward(*args)
        return self._call_cached(*args)

    # ------------------------------------------------------------ export
    def _to_symbol(self, *sym_inputs):
        """Trace this block into a Symbol graph: own parameters become
        named Variables, ops build graph nodes because every layer's
        hybrid_forward goes through F (here the symbol frontend)."""
        from .. import symbol as sym_mod
        params = {self._strip(name): sym_mod.Variable(name)
                  for name, p in self.params.items()}
        return self.hybrid_forward(sym_mod, *sym_inputs, **params)

    def export(self, path, epoch=0):
        """Export the block as symbol JSON + params servable by
        ``symbol.load`` + Executor / ``Predictor`` / ``Module.load``
        (ref: python/mxnet/gluon/block.py HybridBlock.export).

        Writes ``path-symbol.json`` and ``path-%04d.params``.  The
        block must have run forward at least once (shapes settled).
        """
        from .. import symbol as sym_mod
        from ..model import save_checkpoint
        n = getattr(self, "_in_arity", 1)
        names = ["data"] if n == 1 else [f"data{i}" for i in range(n)]
        out = self._to_symbol(*[sym_mod.Variable(nm) for nm in names])
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(out)
        aux_names = set(out.list_auxiliary_states())
        arg, aux = {}, {}
        for name, p in self.collect_params().items():
            (aux if name in aux_names else arg)[name] = p.data()
        save_checkpoint(path, epoch, out, arg, aux)
        return out

    def forward(self, *args):
        """Eager path: hybrid_forward with nd + concrete params."""
        from .. import nd as nd_mod
        params = self._materialized_params(args)
        return self.hybrid_forward(nd_mod, *args, **params)

    def _materialized_params(self, args):
        try:
            return {self._strip(name): p.data()
                    for name, p in self.params.items()}
        except DeferredInitializationError:
            self._finish_deferred(args)
            return {self._strip(name): p.data()
                    for name, p in self.params.items()}

    def _strip(self, name):
        # strip the parameter DICT's prefix, not the block's: a block
        # built with params=other.params shares the donor dict and
        # with it the donor's prefix (weight tying, ref: gluon
        # word_language_model model.py tie_weights) — its param
        # names carry the donor prefix while self.prefix differs
        pfx = self.params.prefix
        if name.startswith(pfx):
            return name[len(pfx):]
        return name[len(self.prefix):] if \
            name.startswith(self.prefix) else name

    def _finish_deferred(self, args):
        """Infer deferred shapes from input shapes via layer hook."""
        self.shape_from_input(*[a for a in args
                                if isinstance(a, NDArray)])
        for _, p in self.params.items():
            if p._deferred_init is not None and p._shape_known():
                p._finish_deferred_init(p.shape)

    def shape_from_input(self, *inputs):
        """Layers with deferred params override to set shapes."""
        raise DeferredInitializationError(
            f"{self.name}: parameter shapes unknown; construct with "
            "explicit in_units/in_channels or run initialize() after "
            "setting shapes")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------ cached
    def _trace_symbol(self, template):
        """Trace this block into a Symbol graph for CachedOp's
        graph-optimized replay path; returns ``(symbol,
        input_names)``.  Tensor argument slots become Variables,
        canonicalized static args pass through to hybrid_forward
        verbatim."""
        from .. import symbol as sym_mod
        names = []

        def make_tensor(i):
            nm = f"data{i}"
            names.append(nm)
            return sym_mod.Variable(nm)

        out = self._to_symbol(*template.flat_args(make_tensor))
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        return out, names

    def _call_cached(self, *args):
        if self._cached_op is None:
            # settle deferred shapes: one eager forward lets each layer
            # infer its own param shapes from its actual input (the
            # reference's deferred-init pass, ref: block.py
            # _deferred_infer_shape); then build the replay cache
            if any(p._deferred_init is not None
                   for _, p in self.collect_params().items()):
                with autograd.pause():
                    self.forward(*args)
            from ..graph.cached_op import CachedOp
            self._cached_op = CachedOp(self)
        from ..graph.cached_op import UnsupportedSignatureError
        try:
            return self._cached_op(*args)
        except UnsupportedSignatureError as exc:
            # this CALL cannot be replay-cached; later calls with
            # keyable arguments still hit the cache (warn only once)
            if not self._cache_fallback:
                self._cache_fallback = True
                from ..utils.log import get_logger
                get_logger().warning(
                    "%s: arguments cannot key a replay cache (%s); "
                    "this call runs eagerly", self.name, exc)
            return self.forward(*args)


class SymbolBlock(HybridBlock):
    """Wrap a Symbol + params as a Block (ref: block.py SymbolBlock)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=None)
        from ..symbol.symbol import Symbol, Group
        if isinstance(outputs, (list, tuple)):
            outputs = Group(outputs)
        self._symbol = outputs
        self._graph_fn = None
        self._inputs = inputs if isinstance(inputs, (list, tuple)) \
            else [inputs]
        input_names = {i.name for i in self._inputs}
        for name in outputs.list_inputs():
            if name not in input_names:
                self._params.get(
                    name, allow_deferred_init=True, grad_req="write")
        if params is not None:
            for name, v in params.items():
                if name in self._params.keys():
                    self._params[name].set_data(v)

    def _trace_symbol(self, template):
        """CachedOp graph path: the wrapped Symbol IS the trace."""
        if not template.is_flat or len(template.tensor_nds) != \
                len(self._inputs):
            raise TypeError(
                f"{self.name}: expected {len(self._inputs)} tensor "
                "arguments for the wrapped symbol")
        return self._symbol, [i.name for i in self._inputs]

    def forward(self, *args):
        from ..executor import build_graph_fn
        arg_vals = {}
        for i, a in zip(self._inputs, args):
            arg_vals[i.name] = a._data
        for name, p in self.params.items():
            arg_vals[name] = p.data()._data
        if self._graph_fn is None:
            # built once, not per call: eager SymbolBlock forwards
            # used to rebuild the whole evaluation closure every
            # invocation
            self._graph_fn = build_graph_fn(self._symbol)
        outs, _ = self._graph_fn(arg_vals, {},
                                 random_state.next_key(),
                                 autograd.is_training())
        outs = [NDArray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError
