"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py — _init_kvstore:102,
step pushes grads / pulls weights per parameter).

TPU-native: the default hot path is a *fused in-jit update* — one
compiled call applying the optimizer to the whole parameter pytree
(with the reference's per-parameter lr_mult/wd_mult semantics as
multiplier trees), instead of the reference's per-parameter Python
loop of push/pull/updater calls.  Learning rate and grad rescale are
traced scalars, so schedulers run without recompiles.

With kvstore='tpu' gradients are already mesh-reduced inside the
compiled step that produced them (psum via sharding), so step() is
just the fused optimizer application; 'device'/'local' behave the
same on one process.  Optimizers without a functional counterpart
(see parallel.optim.from_imperative) fall back to the eager per-param
updater loop transparently.
"""

import jax
import jax.numpy as jnp

from .. import optimizer as opt_mod
from .. import telemetry
from .. import tracing
from ..model import _create_kvstore
from ..parallel import optim as foptim

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._scale = 1.0
        optimizer_params = dict(optimizer_params or {})
        if isinstance(optimizer, str):
            idx2name = {i: p.name for i, p in enumerate(self._params)}
            self._optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name, **optimizer_params)
        else:
            self._optimizer = optimizer
        for i, p in enumerate(self._params):
            self._optimizer.set_lr_mult({p.name: p.lr_mult})
            self._optimizer.set_wd_mult({p.name: p.wd_mult})
        self._updater = opt_mod.get_updater(self._optimizer)
        # step sentinel (docs/numeric_stability.md): guard policy and
        # loss scaler come from the MXTPU_NONFINITE_POLICY /
        # MXTPU_LOSS_SCALE* env knobs; both default to inert
        from .. import resilience
        self._scaler = opt_mod.LossScaler()
        self._guard = resilience.NumericGuard(name="gluon.Trainer")
        telemetry.maybe_start_emitter()
        if self._scaler.dynamic and not self._guard.enabled:
            # dynamic loss scaling IS skip-on-overflow: the scaler's
            # overflow signal is the guard's finiteness flag, and an
            # overflow step must not reach the weights
            self._guard.policy = "skip"
        # device-memory attribution (docs/observability.md): weakref
        # providers so a dropped Trainer stops being counted
        def _param_arrays(tr):
            return [p._data._data for p in tr._params
                    if p._data is not None]

        def _opt_arrays(tr):
            leaves = []
            fstate = getattr(tr, "_fstate", None)
            if fstate is not None:
                leaves += jax.tree_util.tree_leaves(fstate)
            states = getattr(tr._updater, "states", None)
            if states:
                leaves += tracing.updater_state_arrays(states)
            return leaves

        self._mem_unregister = tracing.register_param_opt_providers(
            self, _param_arrays, _opt_arrays)
        self._perf_clock = None
        self._kvstore_spec = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._fopt = None        # functional optimizer (fused path)
        self._fstate = None
        self._fused_update = None
        self._mesh = None
        if kvstore == "tpu":
            # capture the ambient mesh NOW: step() may run outside the
            # use_mesh() scope, and re-resolving there would replicate
            # params over a different device set than the gradients
            from ..parallel import current_mesh, make_mesh
            self._mesh = current_mesh() or make_mesh()
            # replicate now so the *first* forward on a 'dp'-sharded
            # batch already computes distributed (step() comes later)
            self._replicate_params()

    def _replicate_params(self):
        from ..parallel import replicated
        rep = replicated(self._mesh)
        for p in self._params:
            if p._data is not None:
                p._data._data = jax.device_put(p._data._data, rep)

    @property
    def learning_rate(self):
        return self._optimizer.lr

    @property
    def loss_scale(self):
        """Current loss scale — when loss scaling is enabled
        (MXTPU_LOSS_SCALE*), multiply the loss by this before
        ``backward()``; ``step()`` rescales the gradients back."""
        return self._scaler.scale

    @property
    def guard(self):
        """The step sentinel's NumericGuard (skip/bad-step counters,
        host-read accounting)."""
        return self._guard

    def arm_perf(self, flops_per_step=0.0, bytes_per_step=0.0,
                 tokens_per_step=0.0, dtype=None):
        """Arm MFU/roofline gauges (docs/observability.md).

        The Trainer has no graph to cost, so the caller supplies the
        per-step work — e.g. ``perf.transformer_train_flops_per_token``
        times tokens/step, or ``net.train_flops_per_token(...)``.  The
        clock is wall-clock only: ``step()`` ticks it and it publishes
        ``train_mfu``/``train_mbu``/``train_tokens_per_sec`` every
        MXTPU_PERF_INTERVAL steps with zero device reads."""
        from ..perf import TrainPerfClock
        dev = jax.devices()[0]
        if dtype is None:
            dtype = "bfloat16" if dev.platform == "tpu" else "float32"
        self._perf_clock = TrainPerfClock(
            flops_per_step=flops_per_step,
            bytes_per_step=bytes_per_step,
            tokens_per_step=tokens_per_step, device=dev, dtype=dtype)
        return self._perf_clock

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        """(ref: trainer.py:102)"""
        if self._kvstore_spec == "tpu":
            # mesh path: parameters replicated over the ambient mesh
            # (done in __init__, repeated here for deferred-init
            # parameters); grads were already mesh-reduced inside the
            # computation that produced them; no store object needed
            self._replicate_params()
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            arg_params = {p.name: p.data() for p in self._params}
            kv, update_on_kvstore = _create_kvstore(
                self._kvstore_spec, 1, arg_params)
            self._kvstore = kv
            self._update_on_kvstore = update_on_kvstore and \
                kv is not None
            if kv is not None:
                for i, p in enumerate(self._params):
                    kv.init(i, p.data())
                if self._update_on_kvstore:
                    kv.set_optimizer(self._optimizer)
        self._kv_initialized = True

    # ---------------------------------------------------------- fused
    def _init_fused(self):
        """Resolve the functional optimizer for the one-jit-call
        whole-tree update (None counterpart -> eager loop)."""
        opt = self._optimizer
        self._fopt = foptim.from_imperative(opt)
        if self._fopt is None:
            self._fused_update = False  # sentinel: use eager loop
            return
        self._fused_update = {}  # per stale-grad-mask compiled variants
        self._fstate = self._fopt.init(
            {p.name: p.data()._data for p in self._params})

    def _fused_variant(self, missing_names, guarded=False,
                       select=False):
        """Compiled update skipping ``missing_names`` (stale grads):
        the reference leaves both weight and optimizer state of a
        grad-less parameter untouched, so the fused step restores
        those leaves after the whole-tree update.

        With ``guarded=True`` the executable additionally reduces
        the gradients to one finiteness scalar, returned as a third
        output for the guard's interval read.  ``select=True``
        (policies that drop bad updates — skip/raise) further routes
        the whole update through a ``where(finite, new, old)`` select
        so a bad step never reaches weights or optimizer state, on
        device, with zero host syncs; under policy=warn the select
        stays off — warn's contract is to apply the update anyway."""
        fn = self._fused_update.get((missing_names, guarded, select))
        if fn is not None:
            return fn
        opt, fopt = self._optimizer, self._fopt
        lr_mults = {p.name: opt.lr_mult.get(p.name, 1.0)
                    for p in self._params}
        wd_mults = foptim.default_wd_mults(
            [p.name for p in self._params], opt.wd_mult)

        def upd(params, grads, state, scale, lr):
            new_p, new_s = fopt.update(params, grads, state,
                                       scale=scale, lr=lr,
                                       lr_mults=lr_mults,
                                       wd_mults=wd_mults)
            if missing_names:
                new_p = dict(new_p)
                for n in missing_names:
                    new_p[n] = params[n]
                new_s = {k: ({**v, **{n: state[k][n]
                                      for n in missing_names if n in v}}
                             if isinstance(v, dict) else v)
                         for k, v in new_s.items()}
            if not guarded:
                return new_p, new_s
            finite = jnp.asarray(
                opt_mod.all_finite(list(grads.values())))
            if not select:
                return new_p, new_s, finite
            sel = lambda new, old: jax.tree_util.tree_map(
                lambda a, b: jnp.where(finite, a, b), new, old)
            return sel(new_p, params), sel(new_s, state), finite

        fn = jax.jit(upd, donate_argnums=(0, 2))
        self._fused_update[(missing_names, guarded, select)] = fn
        return fn

    def _fused_active(self):
        if self._fused_update in (None, False):
            return False
        kv = self._kvstore
        return not (kv is not None
                    and getattr(kv, "num_workers", 1) > 1)

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimizer step scaled by 1/batch_size
        (ref: trainer.py step).

        With the step sentinel on (MXTPU_NONFINITE_POLICY=skip, or
        dynamic loss scaling), a step whose gradients are non-finite
        is dropped whole: weights, optimizer state, and the
        LR-schedule step count stay untouched, and in multi-rank runs
        the skip decision is allreduced so every replica agrees."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._fused_update is None:
            self._init_fused()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._scaler.active:
            # gradients were computed on a loss multiplied by
            # self.loss_scale; scale them back in the same fused
            # rescale the batch-size division uses
            self._optimizer.rescale_grad /= self._scaler.scale

        missing = [p for p in self._params if p._grad is None]
        if missing and not ignore_stale_grad:
            raise UserWarning(
                f"Gradient of Parameter `{missing[0].name}` not set; "
                "call backward first, or set ignore_stale_grad=True")

        telemetry.counter("train_steps_total").inc()
        if self._perf_clock is not None:
            # wall-clock only: no device reads added to the step
            self._perf_clock.tick()
        guarded = self._guard.enabled
        if self._fused_active():
            with telemetry.span("optimizer"):
                params = {p.name: p.data()._data
                          for p in self._params}
                grads = {p.name: (p._grad._data
                                  if p._grad is not None
                                  else jnp.zeros_like(p.data()._data))
                         for p in self._params}
                if guarded:
                    poison = opt_mod.grad_poison()
                    if poison is not None:
                        first = next(iter(grads))
                        grads[first] = grads[first] * poison
                fn = self._fused_variant(
                    tuple(sorted(p.name for p in missing)), guarded,
                    self._guard.drops_updates)
                out = fn(
                    params, grads, self._fstate,
                    jnp.asarray(self._optimizer.rescale_grad,
                                jnp.float32),
                    jnp.asarray(foptim.scheduled_lr(self._optimizer),
                                jnp.float32))
                if guarded:
                    new_p, self._fstate, flag = out
                else:
                    new_p, self._fstate = out
                for p in self._params:
                    p._data._data = new_p[p.name]
            if guarded:
                due = self._guard.begin_step()
                opt_mod.accumulate_window(self._guard, flag)
                if due:
                    # the guard-interval read is the step's one
                    # device->host transfer — the 'host_sync' slice
                    # of the step timeline (docs/observability.md)
                    with telemetry.span("host_sync"):
                        bad = opt_mod.read_window_bad(self._guard)
                    if bad and self._guard.drops_updates:
                        # the in-jit select already dropped those
                        # updates on device; un-advance the LR
                        # schedule by the exact count (before record,
                        # which may raise under policy=raise)
                        self._optimizer.num_update -= bad
                    self._scaler.update(overflow=bad > 0)
                    self._guard.record(bad == 0,
                                       dropped=max(bad, 1))
            return

        if guarded:
            grads = [p._grad for p in self._params
                     if p._grad is not None]
            if not opt_mod.guarded_step_begin(self._guard,
                                              self._scaler, grads):
                return
        with telemetry.span("optimizer"):
            for i, p in enumerate(self._params):
                if p._grad is None:
                    continue
                if self._kvstore is not None and \
                        self._update_on_kvstore:
                    self._kvstore.push(i, p.grad(), priority=-i)
                    self._kvstore.pull(i, out=p.data(), priority=-i)
                elif self._kvstore is not None:
                    self._kvstore.push(i, p.grad(), priority=-i)
                    self._kvstore.pull(i, out=p.grad(), priority=-i)
                    self._updater(i, p.grad(), p.data())
                else:
                    self._updater(i, p.grad(), p.data())

    def allreduce_grads(self):
        """Explicit grad reduction without update (API parity; on a
        mesh the psum already happened inside the compiled step)."""
        if not self._kv_initialized:
            self._init_kvstore()

    def update(self, batch_size, ignore_stale_grad=False):
        self.step(batch_size, ignore_stale_grad)

    def save_states(self, fname):
        import pickle

        from .. import resilience
        if self._fused_active() and self._fstate is not None:
            import numpy as np
            tree = jax.tree_util.tree_map(np.asarray, self._fstate)
            resilience.atomic_save(
                fname, lambda f: pickle.dump({"fused": tree}, f))
            return
        resilience.atomic_write_bytes(fname,
                                      self._updater.get_states())

    def load_states(self, fname):
        import pickle

        from .. import resilience
        raw = resilience.read_validated_bytes(fname)
        # decode under the corruption guard, apply outside it
        obj = resilience.decode_or_corrupt(
            fname, lambda: pickle.loads(raw))
        if isinstance(obj, dict) and "fused" in obj:
            if self._fused_update is None:
                self._init_fused()
            if not self._fused_active():
                raise ValueError(
                    "states file was saved by the fused update path "
                    "but this Trainer's optimizer has no functional "
                    "counterpart (or runs on a multi-worker kvstore); "
                    "cannot restore")
            self._fstate = jax.tree_util.tree_map(jnp.asarray,
                                                  obj["fused"])
            return
        self._updater.set_states(obj)
