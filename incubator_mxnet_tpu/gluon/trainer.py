"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py — _init_kvstore:102,
step pushes grads / pulls weights per parameter).

TPU-native: with kvstore='tpu' gradients are already mesh-reduced
inside the compiled step (psum via sharding), so step() is just the
optimizer application; the kvstore path is kept for API parity and
multi-process setups.
"""
from .. import optimizer as opt_mod
from ..model import _create_kvstore

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._scale = 1.0
        optimizer_params = dict(optimizer_params or {})
        if isinstance(optimizer, str):
            idx2name = {i: p.name for i, p in enumerate(self._params)}
            self._optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name, **optimizer_params)
        else:
            self._optimizer = optimizer
        for i, p in enumerate(self._params):
            self._optimizer.set_lr_mult({p.name: p.lr_mult})
            self._optimizer.set_wd_mult({p.name: p.wd_mult})
        self._updater = opt_mod.get_updater(self._optimizer)
        self._kvstore_spec = kvstore
        self._kvstore = None
        self._kv_initialized = False

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        """(ref: trainer.py:102)"""
        arg_params = {p.name: p.data() for p in self._params}
        kv, update_on_kvstore = _create_kvstore(
            self._kvstore_spec, 1, arg_params)
        self._kvstore = kv
        self._update_on_kvstore = update_on_kvstore and kv is not None
        if kv is not None:
            for i, p in enumerate(self._params):
                kv.init(i, p.data())
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        self._kv_initialized = True

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimizer step scaled by 1/batch_size
        (ref: trainer.py step)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        for i, p in enumerate(self._params):
            if p._grad is None:
                if not ignore_stale_grad:
                    raise UserWarning(
                        f"Gradient of Parameter `{p.name}` not set; "
                        "call backward first, or set "
                        "ignore_stale_grad=True")
                continue
            if self._kvstore is not None and self._update_on_kvstore:
                self._kvstore.push(i, p.grad(), priority=-i)
                self._kvstore.pull(i, out=p.data(), priority=-i)
            elif self._kvstore is not None:
                self._kvstore.push(i, p.grad(), priority=-i)
                self._kvstore.pull(i, out=p.grad(), priority=-i)
                self._updater(i, p.grad(), p.data())
            else:
                self._updater(i, p.grad(), p.data())

    def allreduce_grads(self):
        """Explicit grad reduction without update (API parity; on a
        mesh the psum already happened inside the compiled step)."""
        if not self._kv_initialized:
            self._init_kvstore()

    def update(self, batch_size, ignore_stale_grad=False):
        self.step(batch_size, ignore_stale_grad)

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())
