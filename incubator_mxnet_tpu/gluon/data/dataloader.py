"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py:23-73).

The reference forks worker processes and passes batches back through
POSIX shared memory (its CPUSharedStorageManager role): workers run
``dataset[i]`` + batchify, the parent receives only small descriptors
and maps the batch bytes out of ``/dev/shm``.  Same design here:

* ``num_workers=0``  — synchronous loading in the caller (reference
  parity).
* ``num_workers>0``, ``thread_pool=True`` — thread workers.  No
  pickling and zero setup cost; right when transforms release the GIL
  (numpy/cv2) or the bottleneck is host->HBM transfer anyway.
* ``num_workers>0`` (default) — forked worker *processes*.  Batches
  come back as ``multiprocessing.shared_memory`` segments (one memcpy
  from ``/dev/shm`` into the jax staging buffer), so Python-level
  transforms scale past the GIL exactly like the reference's
  process workers.

.. note:: migration
   Earlier rounds defaulted ``num_workers>0`` to *threads*; processes
   are now the default (reference parity).  Custom ``batchify_fn``s
   that build NDArrays must stay numpy-only under processes (an error
   is raised when an accelerator is live); pass ``thread_pool=True``
   to keep the previous thread-based behavior unchanged.

Workers deliberately touch only numpy: forking a process that has
already initialized an accelerator backend is only safe if the child
never re-enters that runtime, so batchify inside workers produces
numpy arrays and the parent promotes them to NDArray.
"""
import concurrent.futures as _futures
import multiprocessing as _mp
import os
import warnings
from multiprocessing import shared_memory as _shm

import numpy as np

from ...ndarray import array as nd_array
from ...ndarray.ndarray import NDArray
from ...resilience import DataPipelineError, inject
from ...utils.concurrent import bounded_window as _bounded_window
from ...utils.env import get_env
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]

_SHM_PREFIX = "mxtpu_dl_"


def _sweep_segments(prefix):
    """Unlink every /dev/shm segment under ``prefix`` (leaked by a
    dead worker or an abandoned iteration); returns the count."""
    import glob as _glob
    removed = 0
    for path in _glob.glob("/dev/shm/" + prefix + "*"):
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    return removed


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify)."""
    if isinstance(data[0], NDArray):
        return nd_array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = np.asarray(data)
    return nd_array(data)


def _numpy_batchify_fn(data):
    """default_batchify_fn that stays in numpy — run inside workers."""
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [_numpy_batchify_fn(list(i)) for i in data]
    if isinstance(data[0], NDArray):
        _check_fork_safe_ndarray()
        return np.stack([d.asnumpy() for d in data])
    return np.asarray(data)


def _check_fork_safe_ndarray():
    """NDArray samples force the forked child back into the device
    runtime — only safe when the parent's backend is host CPU."""
    if _worker_accel:
        raise RuntimeError(
            "dataset samples are NDArrays but an accelerator backend "
            "is initialized: a forked DataLoader worker cannot touch "
            "the device. Return numpy from the dataset (transform on "
            "host), or use thread_pool=True / num_workers=0.")


def _accel_backend_initialized():
    """True iff an accelerator backend is ALREADY live in this
    process.  Must never initialize one (probing via
    jax.default_backend() would itself claim the device and spawn the
    runtime threads whose post-fork use the flag exists to prevent);
    an uninitialized jax is fork-safe by definition.  If the probe
    API is gone in a future jax, fail CLOSED (assume an accelerator)
    rather than risk a silent post-fork deadlock."""
    try:
        from jax._src import xla_bridge as _xb
        if not _xb.backends_are_initialized():
            return False
        return any(p != "cpu" for p in _xb._backends)
    except Exception:
        return True


def _dtype_from_name(name):
    """dtype.name round-trip that also covers ml_dtypes extension
    dtypes (bfloat16, fp8...), whose .str is an opaque void code."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _tracker_unregister(name):
    """Keep the resource_tracker out of segment lifetime accounting.

    Segment ownership crosses the worker/parent boundary (worker
    creates, parent unlinks), which the per-process tracker cannot
    model — left registered it both double-unlinks and warns at exit.
    """
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister("/" + name.lstrip("/"),
                                    "shared_memory")
    except Exception:
        pass


def _to_shm(obj, prefix):
    """Recursively move numpy payloads into shared-memory descriptors."""
    if isinstance(obj, NDArray):          # custom batchify may produce
        _check_fork_safe_ndarray()
        inner = _to_shm(obj.asnumpy(), prefix)
        if inner[0] == "np":
            return ("nd",) + inner[1:]
        return ("ndpy", inner[1])         # zero-size: carried inline
    if isinstance(obj, np.ndarray) and obj.nbytes > 0:
        arr = np.ascontiguousarray(obj)
        seg = _shm.SharedMemory(
            create=True, size=arr.nbytes,
            name=prefix + os.urandom(8).hex())
        # the parent unlinks; unregister here so the worker-side
        # tracker does not also try to (unlink() re-unregisters)
        _tracker_unregister(seg.name)
        view = np.frombuffer(seg.buf, dtype=arr.dtype).reshape(arr.shape)
        view[...] = arr
        del view                        # release the buffer export
        name = seg.name
        seg.close()
        return ("np", name, arr.shape, arr.dtype.name)
    if isinstance(obj, (list, tuple)):
        return ("seq", type(obj) is tuple,
                [_to_shm(o, prefix) for o in obj])
    return ("py", obj)


def _from_shm(desc):
    """Parent side: map descriptors back; one memcpy out of /dev/shm.

    Attaching registers the name with the parent's resource tracker
    and ``unlink()`` unregisters it, so no manual tracker bookkeeping
    is needed here.
    """
    tag = desc[0]
    if tag in ("np", "nd"):
        _, name, shape, dtype = desc
        seg = _shm.SharedMemory(name=name)
        try:
            arr = np.frombuffer(
                seg.buf, dtype=_dtype_from_name(dtype)).reshape(shape)
            out = arr.copy()        # never alias the shm page: jax's
            del arr                 # CPU device_put may zero-copy
        finally:
            seg.close()
            seg.unlink()
        return nd_array(out) if tag == "nd" else out
    if tag == "ndpy":
        return nd_array(desc[1])
    if tag == "seq":
        _, is_tuple, items = desc
        items = [_from_shm(i) for i in items]
        return tuple(items) if is_tuple else items
    return desc[1]


def _promote(obj):
    """numpy → NDArray, preserving the default-batchify list shape."""
    if isinstance(obj, np.ndarray):
        return nd_array(obj)
    if isinstance(obj, list):
        return [_promote(o) for o in obj]
    if isinstance(obj, tuple):
        return tuple(_promote(o) for o in obj)
    return obj


_worker_dataset = None
_worker_batchify = None
_worker_prefix = None
_worker_accel = False


def _worker_init(dataset, batchify_fn, prefix, accel):
    global _worker_dataset, _worker_batchify, _worker_prefix, \
        _worker_accel
    _worker_dataset = dataset
    _worker_batchify = batchify_fn
    _worker_prefix = prefix
    _worker_accel = accel


def _worker_fn(indices, token):
    """Build one batch under a per-task shm prefix: when the parent
    declares this task lost (worker died holding it), it can sweep
    exactly this task's segments — completed batches from the same
    worker keep theirs."""
    inject("dataloader", "worker")
    batch = _worker_batchify([_worker_dataset[i] for i in indices])
    return _to_shm(batch, _worker_prefix + token + "_")


class DataLoader:
    """(ref: dataloader.py DataLoader)"""

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size required unless batch_sampler given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle and sampler are exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._batches_served = 0
        self._epoch_rng = None
        self._resume = None

    # ------------------------------------------------- resumable state
    def state_dict(self):
        """Checkpointable position: batches served this epoch + the
        numpy RNG state snapshotted when the epoch's iteration began
        (the sampler's shuffle source), so a restore replays the same
        sampler order and skips exactly the served batches."""
        if self._resume is not None:
            return dict(self._resume)    # armed but not yet applied
        rng = self._epoch_rng if self._epoch_rng is not None \
            else np.random.get_state()
        return {"type": "DataLoader",
                "batches_served": self._batches_served,
                "epoch_rng": rng}

    def load_state_dict(self, state):
        """Arm a resume: the next ``iter()`` restores the saved RNG
        state, regenerates the identical sampler order, and skips the
        already-served index batches without loading their data."""
        if state.get("type") != "DataLoader":
            raise ValueError(
                f"state_dict type {state.get('type')!r} does not "
                "match DataLoader")
        self._resume = dict(state)

    def _sampler_batches(self, skip):
        for j, idxs in enumerate(self._batch_sampler):
            if j < skip:
                continue
            yield idxs

    def __iter__(self):
        resume, self._resume = self._resume, None
        if resume is not None:
            np.random.set_state(resume["epoch_rng"])
            skip = int(resume["batches_served"])
        else:
            skip = 0
        self._epoch_rng = np.random.get_state()
        self._batches_served = skip
        batches = self._sampler_batches(skip)
        batchify = self._batchify_fn or default_batchify_fn
        if self._num_workers == 0:
            for batch in batches:
                out = batchify([self._dataset[i] for i in batch])
                self._batches_served += 1
                yield out
            return
        if self._thread_pool:
            with _futures.ThreadPoolExecutor(self._num_workers) as pool:
                def submit(idxs):
                    return pool.submit(
                        lambda: batchify(
                            [self._dataset[i] for i in idxs]))
                for fut in _bounded_window(
                        batches, submit, 2 * self._num_workers):
                    out = fut.result()
                    self._batches_served += 1
                    yield out
            return
        yield from self._iter_multiprocess(batches)

    def _iter_multiprocess(self, batches):
        # fork: the dataset is inherited copy-on-write (no pickling);
        # workers are numpy-only so re-entering an already-initialized
        # accelerator runtime in the child never happens.
        # the NDArray-building default batchify must not run in the
        # forked child (creating jax arrays re-enters the inherited
        # PJRT client, which can deadlock): substitute the numpy
        # equivalent and promote to NDArray in the parent.  Custom
        # batchify fns must themselves stay numpy-only in workers.
        if (self._batchify_fn is None
                or self._batchify_fn is default_batchify_fn):
            worker_batchify, promote = _numpy_batchify_fn, _promote
        else:
            worker_batchify, promote = self._batchify_fn, lambda b: b
        # unique per-iteration segment prefix: in-flight batches whose
        # descriptors never reach the parent (early abandon, crash)
        # are reclaimed by the glob below once the workers are dead
        prefix = "%s%x_%s_" % (_SHM_PREFIX, os.getpid(),
                               os.urandom(4).hex())
        accel = _accel_backend_initialized()
        with warnings.catch_warnings():
            # the at-fork warnings do not apply (the children are
            # numpy-only), but only those two specific warnings are
            # known-benign — anything else about fork must surface
            warnings.filterwarnings(
                "ignore", category=RuntimeWarning,
                message=r"os\.fork\(\) was called\.")
            warnings.filterwarnings(
                "ignore", category=DeprecationWarning,
                message=r"This process .* is multi-threaded")
            pool = _mp.get_context("fork").Pool(
                self._num_workers, initializer=_worker_init,
                initargs=(self._dataset, worker_batchify, prefix,
                          accel))
        try:
            import itertools as _it
            import time as _time
            grace = get_env("MXTPU_DL_DEAD_GRACE")
            max_restarts = get_env("MXTPU_DATA_WORKER_RESTARTS")
            restarts_used = 0
            tokens = _it.count()
            # respawn-generation bookkeeping: a task is only suspect
            # if the worker set changed AFTER it was submitted.  A
            # global "pids look healthy now" snapshot cannot express
            # that (a batch completing after a respawn would reset it
            # and mask an earlier lost task forever).
            known_pids = {w.pid for w in getattr(pool, "_pool", [])}
            respawn_gen = 0

            def _observe_pids():
                nonlocal known_pids, respawn_gen
                pids = {w.pid for w in getattr(pool, "_pool", [])}
                if pids != known_pids:
                    respawn_gen += 1
                    known_pids = pids
                return respawn_gen

            def _submit(idxs):
                # observe at submission: a respawn that happened
                # while no result was being polled must not count
                # against tasks submitted after it
                token = "%x" % next(tokens)
                return (pool.apply_async(_worker_fn, (idxs, token)),
                        _observe_pids(), idxs, token)

            for res, submit_gen, idxs, token in _bounded_window(
                    batches, _submit, 2 * self._num_workers):
                # poll with a timeout: if a worker dies hard (native
                # segfault, OOM-kill), Pool respawns it but the lost
                # task's result never arrives — a bare get() would
                # hang the training loop forever.  A respawn alone is
                # not proof THIS result is lost (the died worker may
                # have held a different task), so a result submitted
                # before the respawn gets a grace window to arrive.
                # A task declared lost has its half-built segments
                # swept and its index batch re-dispatched to the
                # (Pool-respawned) workers, up to the
                # MXTPU_DATA_WORKER_RESTARTS budget.
                deadline = None
                data_timeout = get_env("MXTPU_DATA_TIMEOUT")
                hard_deadline = _time.monotonic() + data_timeout \
                    if data_timeout > 0 else None
                while True:
                    try:
                        desc = res.get(1.0)
                        break
                    except _mp.TimeoutError:
                        if _observe_pids() == submit_gen:
                            # no respawn since (re)submission.  Only
                            # here does the absolute backstop apply —
                            # a pool wedged with no death evidence
                            # (e.g. a worker killed at the worst
                            # moment) must still bound the wait.  A
                            # respawn hands over to the grace +
                            # re-dispatch path below instead, so a
                            # short MXTPU_DATA_TIMEOUT can never
                            # preempt the recovery budget
                            if hard_deadline is not None and \
                                    _time.monotonic() > hard_deadline:
                                raise DataPipelineError(
                                    "DataLoader: no batch arrived "
                                    f"within {data_timeout:g}s "
                                    "(MXTPU_DATA_TIMEOUT); the "
                                    "worker pool is stalled — check "
                                    "dataset __getitem__ for hangs "
                                    "or raise the timeout for slow "
                                    "sources") from None
                            continue
                        if deadline is None:
                            deadline = _time.monotonic() + grace
                            continue
                        if _time.monotonic() <= deadline:
                            continue
                        _sweep_segments(prefix + token + "_")
                        if restarts_used >= max_restarts:
                            raise DataPipelineError(
                                "a DataLoader worker died and its "
                                f"batch never arrived (waited "
                                f"{grace:.0f}s after the respawn, "
                                f"re-dispatched {restarts_used} "
                                "time(s), MXTPU_DATA_WORKER_RESTARTS"
                                f"={max_restarts}); check dataset "
                                "__getitem__/batchify_fn for crashes "
                                "in native code or OOM "
                                "(MXTPU_DL_DEAD_GRACE overrides the "
                                "wait)")
                        restarts_used += 1
                        from ... import telemetry
                        telemetry.counter(
                            "dataloader_worker_restarts_total").inc()
                        warnings.warn(
                            "a DataLoader worker died holding batch "
                            f"{idxs[:4]}{'...' if len(idxs) > 4 else ''}; "
                            "re-dispatching it (restart "
                            f"{restarts_used}/{max_restarts})",
                            RuntimeWarning)
                        token = "%x" % next(tokens)
                        res = pool.apply_async(_worker_fn,
                                               (idxs, token))
                        submit_gen = _observe_pids()
                        deadline = None
                        if hard_deadline is not None:
                            # fresh dispatch, fresh backstop window
                            hard_deadline = _time.monotonic() \
                                + data_timeout
                    except Exception as exc:
                        # a worker that *raised* (vs died): surface
                        # as a typed pipeline failure with the cause
                        raise DataPipelineError(
                            "DataLoader worker raised "
                            f"{type(exc).__name__}: {exc}") from exc
                out = promote(_from_shm(desc))
                self._batches_served += 1
                yield out
        finally:
            pool.terminate()
            pool.join()
            _sweep_segments(prefix)

    def __len__(self):
        return len(self._batch_sampler)
