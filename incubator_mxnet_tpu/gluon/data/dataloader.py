"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py:23-73).

The reference forks worker processes passing batches back through
POSIX shared memory (CPUSharedStorageManager).  On TPU the bottleneck
is the host->HBM transfer, not Python-side collation, so workers are
threads (no pickling, zero-copy into the jnp.asarray staging call) —
with num_workers=0 meaning synchronous loading, like the reference.
"""
import concurrent.futures as _futures

import numpy as np

from ...ndarray import array as nd_array
from ...ndarray.ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify)."""
    if isinstance(data[0], NDArray):
        return nd_array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = np.asarray(data)
    return nd_array(data)


class DataLoader:
    """(ref: dataloader.py DataLoader)"""

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size required unless batch_sampler given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle and sampler are exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._batchify_fn(
                    [self._dataset[i] for i in batch])
            return
        with _futures.ThreadPoolExecutor(self._num_workers) as pool:
            futures = [
                pool.submit(lambda idxs=batch: self._batchify_fn(
                    [self._dataset[i] for i in idxs]))
                for batch in self._batch_sampler]
            for f in futures:
                yield f.result()

    def __len__(self):
        return len(self._batch_sampler)
