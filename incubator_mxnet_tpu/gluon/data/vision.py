"""Vision datasets + transforms (ref: python/mxnet/gluon/data/vision.py).

Download-free: datasets read local idx/npz files (zero-egress
environments); FashionMNIST/CIFAR expect pre-fetched files.
"""
import os

import numpy as np

from ...ndarray import array as nd_array
from .dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "ImageFolderDataset",
           "transforms"]


class MNIST(Dataset):
    """MNIST from local idx files (ref: vision.py MNIST)."""

    def __init__(self, root="data/mnist", train=True, transform=None):
        self._transform = transform
        part = "train" if train else "t10k"
        img = os.path.join(root, f"{part}-images-idx3-ubyte")
        lbl = os.path.join(root, f"{part}-labels-idx1-ubyte")
        from ...io.io import _read_idx_images, _read_idx_labels
        self._data = _read_idx_images(
            img if os.path.exists(img) else img + ".gz")
        self._label = _read_idx_labels(
            lbl if os.path.exists(lbl) else lbl + ".gz")

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        data = nd_array(self._data[idx][:, :, None].astype(np.float32))
        label = float(self._label[idx])
        if self._transform is not None:
            return self._transform(data, label)
        return data, label


class FashionMNIST(MNIST):
    def __init__(self, root="data/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(Dataset):
    """CIFAR-10 from local binary batches (ref: vision.py CIFAR10)."""

    def __init__(self, root="data/cifar10", train=True, transform=None):
        self._transform = transform
        files = [f"data_batch_{i}.bin" for i in range(1, 6)] \
            if train else ["test_batch.bin"]
        data, labels = [], []
        for fname in files:
            raw = np.fromfile(os.path.join(root, fname), dtype=np.uint8)
            raw = raw.reshape(-1, 3073)
            labels.append(raw[:, 0])
            data.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                        .transpose(0, 2, 3, 1))
        self._data = np.concatenate(data)
        self._label = np.concatenate(labels)

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        data = nd_array(self._data[idx].astype(np.float32))
        label = float(self._label[idx])
        if self._transform is not None:
            return self._transform(data, label)
        return data, label


class ImageFolderDataset(Dataset):
    """Folder-per-class image dataset (ref: vision.py
    ImageFolderDataset); decoding via image package."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fn in sorted(os.listdir(path)):
                if fn.lower().endswith((".jpg", ".jpeg", ".png",
                                        ".bmp", ".npy")):
                    self.items.append((os.path.join(path, fn), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        fname, label = self.items[idx]
        from ...image import imread
        img = imread(fname, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class transforms:
    """Minimal transform zoo (later reference versions' gluon.data
    .vision.transforms surface)."""

    class Compose:
        def __init__(self, trans):
            self._trans = trans

        def __call__(self, x):
            for t in self._trans:
                x = t(x)
            return x

    class ToTensor:
        """HWC uint8 [0,255] -> CHW float32 [0,1]."""

        def __call__(self, x):
            arr = x.asnumpy() if hasattr(x, "asnumpy") else np.asarray(x)
            return nd_array(arr.transpose(2, 0, 1).astype(np.float32)
                            / 255.0)

    class Normalize:
        def __init__(self, mean, std):
            self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
            self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)

        def __call__(self, x):
            arr = x.asnumpy() if hasattr(x, "asnumpy") else np.asarray(x)
            return nd_array((arr - self._mean) / self._std)
