"""Gluon: the imperative frontend (ref: python/mxnet/gluon/)."""
from .parameter import Parameter, ParameterDict
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import data
from . import utils
from . import rnn
from . import model_zoo
from . import contrib

__all__ = ["Parameter", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "nn", "loss", "data", "utils",
           "rnn", "model_zoo"]
