"""Prefix cache: shared-prompt KV reuse over the block pool.

Serving traffic is dominated by a few system prompts fanned out
across many requests.  Full blocks of prompt KV are content-
addressed by a rolling token hash, so a request whose prompt starts
with an already-served prefix adopts those blocks COPY-FREE — its
block table points at the cached ids (refcounted by
block_table.BlockPool) and prefill recomputes only the suffix.

Keying: block ``i`` of a token stream is identified by the hash
chain ``key_i = hash((key_{i-1},) + tokens[i*bs:(i+1)*bs])`` — O(1)
memory per entry, and a block only matches when its ENTIRE token
history matches (not just its own tokens).  Hash collisions are
possible in principle (64-bit Python hashes) but would need two
distinct token histories colliding on the same chain; acceptable for
a cache whose failure mode is visible wrong output under adversarial
prompts, and the trade is documented in docs/serving.md.

Matching stops at ``(len(tokens) - 1) // block_size`` full blocks:
the LAST prompt token is always left to the suffix so prefill has at
least one query row to emit first-token logits from.

Eviction is LRU over entries whose block's ONLY remaining holder is
the cache itself — a block still referenced by a running request is
never evicted (the entry just leaves the cache; the request keeps
its context).
"""
from collections import OrderedDict

__all__ = ["PrefixCache"]

_SEED = 0x5eed                      # chain seed, arbitrary non-zero


class PrefixCache:
    """Token-hash -> pool-block map with LRU eviction.

    Owns one refcount on every cached block (taken at
    :meth:`insert`, dropped at eviction), so cached KV survives the
    request that produced it until pool pressure reclaims it.
    """

    def __init__(self, pool, enabled=True):
        self._pool = pool
        self.enabled = bool(enabled)
        self._entries = OrderedDict()       # chain key -> block id

    def __len__(self):
        return len(self._entries)

    @staticmethod
    def _chain(key, block_tokens):
        return hash((key,) + tuple(block_tokens))

    def match(self, tokens):
        """Longest cached chain over the leading full blocks of
        ``tokens`` (at most ``(len-1)//bs`` — see module doc).

        Increfs every matched block (the caller's request becomes a
        holder) and returns ``(block_ids, n_cached_tokens)``."""
        if not self.enabled:
            return [], 0
        bs = self._pool.block_size
        matched = []
        key = _SEED
        for i in range((len(tokens) - 1) // bs):
            key = self._chain(key, tokens[i * bs:(i + 1) * bs])
            bid = self._entries.get(key)
            if bid is None:
                break
            self._entries.move_to_end(key)          # LRU touch
            matched.append(bid)
        if matched:
            self._pool.incref(matched)
        return matched, len(matched) * bs

    def insert(self, tokens, block_ids):
        """Register the full blocks of a just-prefilled token stream
        (``block_ids[i]`` holds positions ``[i*bs, (i+1)*bs)``).

        The cache increfs each NEWLY inserted block; blocks whose
        chain key is already cached (e.g. the matched prefix this
        request adopted) are only LRU-touched — a concurrent
        duplicate prefill keeps the first block registered.  Returns
        the number of new entries."""
        if not self.enabled:
            return 0
        bs = self._pool.block_size
        key = _SEED
        added = 0
        for i in range(len(tokens) // bs):
            key = self._chain(key, tokens[i * bs:(i + 1) * bs])
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            bid = block_ids[i]
            self._pool.incref([bid])
            self._entries[key] = bid
            added += 1
        return added

    def block_refs(self):
        """``{block_id: refs held by the cache}`` — the cache's side
        of the pool leak audit: after an engine drains, every live
        pool block's refcount must be exactly what this returns (the
        chaos and leak-audit tests assert the equality against
        ``BlockPool.live()``, so a terminal path that leaks a
        request's hold on a shared block is caught by id)."""
        refs = {}
        for bid in self._entries.values():
            refs[bid] = refs.get(bid, 0) + 1
        return refs

    def evict(self, n):
        """Free up to ``n`` cache-held blocks in LRU order, skipping
        any still shared with a live request.  Returns blocks
        actually freed."""
        if n <= 0:
            return 0
        freed = 0
        for key in list(self._entries):
            if freed >= n:
                break
            bid = self._entries[key]
            if self._pool.refcount(bid) == 1:       # cache-only
                del self._entries[key]
                self._pool.free([bid])
                freed += 1
        return freed

    def clear(self):
        """Drop every entry (releasing the cache's refs)."""
        for bid in self._entries.values():
            self._pool.free([bid])
        self._entries.clear()
