"""Serving-fleet RPC — back-compat façade over the shared transport.

The framed-RPC implementation moved to ``incubator_mxnet_tpu/rpc.py``
when the remote data-service ranks (docs/data_service.md "Remote
ranks") started speaking the same wire protocol; this module keeps
the historical import surface (`serving.rpc.RpcServer` etc.) alive
for fleet code and tests.  Serving semantics are unchanged: the
default fault-injection scope on every send path is still
``router:net`` (see docs/resilience.md).
"""
from ..rpc import (MAGIC, MAX_FRAME_BYTES, DEFAULT_FAULT_SCOPE,
                   RpcClient, RpcError, RpcFrameError, RpcServer,
                   RpcTimeoutError, default_timeout, encode_frame,
                   logger, recv_frame, send_frame)
from ..rpc import _HEADER, _Conn, _deadline, _recv_exact, _remaining

#: names kept importable for transport internals users (tests build
#: raw frames via "_HEADER", the router pools "_Conn" handles, and
#: deadline math reuses "_deadline" / "_remaining" / "_recv_exact")
_PRIVATE_REEXPORTS = ("_HEADER", "_Conn", "_deadline",
                      "_recv_exact", "_remaining")

__all__ = ["MAGIC", "MAX_FRAME_BYTES", "DEFAULT_FAULT_SCOPE",
           "RpcClient", "RpcError", "RpcFrameError", "RpcServer",
           "RpcTimeoutError", "default_timeout", "encode_frame",
           "logger", "recv_frame", "send_frame"]
