"""Fleet router: spread requests over N replicas, survive their
deaths (docs/serving.md "Fleet").

:class:`ServingRouter` owns one RPC link per replica
(serving/rpc.py) and gives the fleet the same request contract one
:class:`~.engine.ServingEngine` gives a process:

- **Typed admission.**  Fleet-wide queue/token budgets shed at the
  door with the same :class:`ServeRejectedError` the engine raises —
  traffic code cannot tell a fleet from a single engine.
- **Prefix-cache-aware routing.**  The prompt's leading full blocks
  are rolled into the same chain hashes
  :class:`~.cache_manager.PrefixCache` uses (seed ``0x5eed``); the
  replica that most recently served the longest matching chain gets
  the request (its cache likely still holds those blocks), falling
  back to the least-queued healthy replica.
- **Health + circuit breaker.**  Every frame from a replica
  refreshes its link's heartbeat; pings measure EWMA latency.
  Consecutive dispatch failures trip a closed -> open breaker
  (``MXTPU_BREAKER_THRESHOLD``); after
  ``MXTPU_BREAKER_COOLDOWN`` seconds half-open admits EXACTLY one
  probe request — success closes the breaker, failure re-opens it.
- **Failover re-dispatch.**  When a replica dies (link drop, frame
  corruption, staleness) its in-flight requests are re-dispatched to
  survivors carrying their *remaining* deadline budgets and the
  tokens generated so far — greedy recompute makes the continuation
  token-identical.  Dispatch generations dedup stale frames, so a
  request is never duplicated; the router's single finalize point
  plus a deadline net (a request past its deadline with a wedged
  owner expires locally) means every admitted request ends in
  exactly one terminal state fleet-wide, never silently lost.

All timing is monotonic-clock (lint-enforced); deadlines cross the
wire as REMAINING seconds.  SIGTERM latches drain: admission stops,
every replica snapshots and drains, and the fleet can be restored
replica-by-replica (``ServingEngine.restore``).
"""
import os
import threading
import time

from .. import debugz, telemetry, tracing
from ..utils.env import get_env
from ..utils.log import get_logger
from . import rpc
from .cache_manager import _SEED
from .scheduler import (EXPIRED, FAILED, ServeRejectedError,
                        TERMINAL_STATES)

logger = get_logger("serving.router")

_m_requests = telemetry.counter("router_requests_total")
_m_rejected = telemetry.counter("router_rejected_total")
_m_redispatch = telemetry.counter("router_redispatches_total")
_m_rep_fail = telemetry.counter("router_replica_failures_total")
_m_breaker_open = telemetry.counter("router_breaker_open_total")
_m_healthy = telemetry.gauge("fleet_healthy_replicas")
_m_failover = telemetry.histogram("router_failover_seconds")

#: affinity map bound: oldest prefix-chain entries fall off first so
#: a long-lived router cannot grow without bound
_AFFINITY_CAP = 8192


class FleetRequest:
    """Router-side view of one admitted request."""

    __slots__ = ("id", "prompt", "max_new_tokens", "eos_id",
                 "generated", "state", "error", "ttft_done",
                 "submit_ts", "first_token_ts", "deadline_ts",
                 "ttft_deadline_ts", "link", "gen", "redispatches",
                 "done_event", "sink", "_redispatch_ts")

    def __init__(self, rid, prompt, max_new_tokens, eos_id=None):
        self.id = rid
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.generated = []
        self.state = "queued"
        self.error = None
        self.ttft_done = False
        self.submit_ts = time.monotonic()
        self.first_token_ts = None
        self.deadline_ts = None
        self.ttft_deadline_ts = None
        self.link = None          # name of the replica that owns it
        self.gen = 0              # dispatch generation (dedup)
        self.redispatches = 0
        self.done_event = threading.Event()
        self.sink = None          # front-door conn to stream to
        self._redispatch_ts = None

    @property
    def done(self):
        return self.state in TERMINAL_STATES

    @property
    def tokens(self):
        return list(self.prompt) + list(self.generated)


class _Breaker:
    """Closed / open / half-open circuit breaker, monotonic clock.

    ``allow()`` answers "may a dispatch go to this replica now":
    closed -> yes; open -> no until the cooldown elapses, then the
    transition to half-open admits EXACTLY ONE probe (further
    ``allow()`` calls say no while the probe is in flight);
    ``ok()`` closes from any state, ``fail()`` counts toward the
    threshold and re-opens immediately from half-open."""

    def __init__(self, threshold=None, cooldown=None):
        self.threshold = (get_env("MXTPU_BREAKER_THRESHOLD")
                          if threshold is None else int(threshold))
        self.cooldown = (get_env("MXTPU_BREAKER_COOLDOWN")
                         if cooldown is None else float(cooldown))
        self.state = "closed"
        self.failures = 0
        self.open_until = 0.0
        self.probe_rid = None

    def allow(self, now):
        if self.state == "closed":
            return True
        if self.state == "open":
            if now >= self.open_until:
                self.state = "half_open"
                self.probe_rid = None    # set by the dispatch path
                return True
            return False
        # half_open: one probe slot — free until the dispatch path
        # stamps probe_rid, then taken until the probe resolves
        return self.probe_rid is None

    def ok(self):
        self.state = "closed"
        self.failures = 0
        self.probe_rid = None

    def fail(self, now):
        self.failures += 1
        tripped = (self.state == "half_open"
                   or self.failures >= self.threshold)
        if tripped and self.state != "open":
            self.state = "open"
            self.open_until = now + self.cooldown
            self.probe_rid = None
            return True              # newly opened
        if self.state == "open":
            self.open_until = now + self.cooldown
        return False


class _ReplicaLink:
    """One replica: RPC client + reader thread + health state."""

    def __init__(self, name, host, port, router):
        self.name = name
        self.client = rpc.RpcClient(host, port)
        self.router = router
        self.breaker = _Breaker(router.breaker_threshold,
                                router.breaker_cooldown)
        self.inflight = set()       # rids currently owned here
        self.last_heard = 0.0       # monotonic, any frame refreshes
        self.ewma_latency = 0.0     # seconds, from ping RTT
        self.alive = False
        self.drained = False
        self._reader = None
        self._reconnecting = False
        self._pings = {}            # seq -> send ts
        self._ping_seq = 0

    def usable(self, now):
        """May a dispatch be sent here right now (connection up,
        heartbeat fresh, breaker consenting)?"""
        return (self.alive
                and now - self.last_heard <= self.router.stale_after
                and self.breaker.allow(now))

    def healthy(self, now):
        """Health for reporting: up + fresh (breaker state aside)."""
        return (self.alive
                and now - self.last_heard <= self.router.stale_after)

    def connect(self, retry=True):
        if retry:
            self.client.connect_retry()
        else:
            # deadline-ok: RpcClient.connect arms its own per-call
            # connect timeout (rpc.default_timeout)
            self.client.connect()
        self.alive = True
        self.drained = False
        self.last_heard = time.monotonic()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"router-read-{self.name}")
        self._reader.start()

    def _read_loop(self):
        me = threading.current_thread()
        while self.alive and self._reader is me:
            try:
                msg, budget = self.client.recv(
                    timeout=self.router.poll_interval)
            except rpc.RpcTimeoutError:
                continue             # idle tick; staleness is poll()'s call
            except rpc.RpcError:
                if self.alive and self._reader is me:
                    self.router._on_link_down(self, "link lost")
                return
            self.last_heard = time.monotonic()
            self.router._on_frame(self, msg, budget)

    def send(self, msg, budget=0.0):
        self.client.send(msg, budget=budget)

    def ping(self):
        self._ping_seq += 1
        seq = self._ping_seq
        self._pings[seq] = time.monotonic()
        try:
            self.send({"op": "ping", "seq": seq})
        except rpc.RpcError:
            self.router._on_link_down(self, "ping send failed")

    def observe_pong(self, seq):
        sent = self._pings.pop(seq, None)
        if sent is not None:
            rtt = time.monotonic() - sent
            self.ewma_latency = (0.8 * self.ewma_latency
                                 + 0.2 * rtt
                                 if self.ewma_latency else rtt)

    def close(self):
        self.alive = False
        self._reader = None
        self.client.close()


class ServingRouter:
    """Route requests over a replica fleet (see module doc).

    ``replicas`` is a list of ``"host:port"`` strings or
    ``(host, port)`` pairs (default: ``MXTPU_REPLICA_ADDRS``).  The
    router is driven by :meth:`poll` — call it from your serve loop,
    or let :meth:`listen`'s background poller do it."""

    def __init__(self, replicas=None, queue_limit=None,
                 queue_tokens=None, block_size=None,
                 breaker_threshold=None, breaker_cooldown=None,
                 ttft_deadline=None, deadline=None,
                 poll_interval=0.05, stale_after=None,
                 ping_interval=None, expiry_grace=0.5):
        if replicas is None:
            raw = get_env("MXTPU_REPLICA_ADDRS")
            replicas = [a for a in raw.split(",") if a.strip()]
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self.queue_limit = (get_env("MXTPU_SERVE_QUEUE_LIMIT")
                            if queue_limit is None else queue_limit)
        self.queue_tokens = (get_env("MXTPU_SERVE_QUEUE_TOKENS")
                             if queue_tokens is None
                             else queue_tokens)
        self.block_size = (get_env("MXTPU_SERVE_BLOCK_SIZE")
                           if block_size is None else block_size)
        self.ttft_deadline = (get_env("MXTPU_SERVE_TTFT_DEADLINE")
                              if ttft_deadline is None
                              else ttft_deadline)
        self.deadline = (get_env("MXTPU_SERVE_DEADLINE")
                         if deadline is None else deadline)
        self.poll_interval = poll_interval
        self.stale_after = (3.0 * rpc.default_timeout()
                            if stale_after is None else stale_after)
        self.ping_interval = (max(poll_interval * 4, 0.2)
                              if ping_interval is None
                              else ping_interval)
        self.expiry_grace = expiry_grace
        self._lock = threading.RLock()
        self._links = {}
        for i, spec in enumerate(replicas):
            if isinstance(spec, (tuple, list)):
                host, port = spec
            else:
                host, _, port = str(spec).rpartition(":")
            name = f"replica{i}"
            self._links[name] = _ReplicaLink(name, host or
                                             "127.0.0.1",
                                             int(port), self)
        self._live = {}             # rid -> FleetRequest (not terminal)
        self._terminal_ids = set()  # exactly-one-terminal dedup
        self._pending = []          # admitted, awaiting a healthy link
        self._affinity = {}         # chain hash -> link name (FIFO cap)
        self._next_id = 0
        self._draining = False       # admission gate
        self._drain_started = False  # drain frames sent to replicas
        self._drain_requested = False
        self._drained_links = set()
        self._last_ping = 0.0
        self._last_stats = None
        self._frontend = None
        self._poller = None
        self._closed = threading.Event()
        self.snapshot_dir = None    # per-replica drain snapshots

    # ------------------------------------------------------ lifecycle
    def connect(self):
        """Connect every link (full-jitter retries); returns self."""
        for link in self._links.values():
            try:
                # deadline-ok: bounded internally (connect_retry's
                # jittered attempts each arm a connect timeout)
                link.connect()
            except rpc.RpcError as e:
                logger.warning("router: %s unreachable at startup: "
                               "%s", link.name, e)
        self._update_health_gauge()
        return self

    def close(self):
        self._closed.set()
        if self._frontend is not None:
            self._frontend.close()
        for link in self._links.values():
            link.close()
        if self._poller is not None:
            self._poller.join(timeout=2.0)

    # ------------------------------------------------------ admission
    def _reject(self, reason, n_tokens):
        _m_rejected.inc()
        tracing.trace_event("router_reject", reason=reason,
                            n_tokens=n_tokens)
        raise ServeRejectedError(
            f"fleet admission rejected request ({reason}); "
            "retry later or scale the fleet")

    def submit(self, tokens, max_new_tokens, eos_id=None,
               ttft_deadline=None, deadline=None):
        """Admit one request fleet-wide; returns a
        :class:`FleetRequest` whose ``done_event`` fires at its
        single terminal state.  Raises :class:`ServeRejectedError`
        exactly like ``ServingEngine.submit`` when draining or over
        the fleet queue/token budgets."""
        tokens = [int(t) for t in tokens]
        with self._lock:
            if self._draining:
                self._reject("draining", len(tokens))
            if self.queue_limit and len(self._live) >= \
                    self.queue_limit:
                self._reject("queue_limit", len(tokens))
            if self.queue_tokens:
                queued = sum(len(r.prompt)
                             for r in self._live.values())
                if queued + len(tokens) > self.queue_tokens:
                    self._reject("queue_tokens", len(tokens))
            rid = self._next_id
            self._next_id += 1
            req = FleetRequest(rid, tokens, max_new_tokens,
                               eos_id=eos_id)
            now = time.monotonic()
            ttft = (self.ttft_deadline if ttft_deadline is None
                    else ttft_deadline)
            total = self.deadline if deadline is None else deadline
            if ttft:
                req.ttft_deadline_ts = now + ttft
            if total:
                req.deadline_ts = now + total
            self._live[rid] = req
            _m_requests.inc()
            self._dispatch(req)
        return req

    def cancel(self, rid):
        """Propagate cancellation; the owning replica's cancel
        terminal (or the deadline net) finalizes the request."""
        with self._lock:
            req = self._live.get(rid)
            if req is None or req.done:
                return False
            link = self._links.get(req.link)
        if link is not None and link.alive:
            try:
                link.send({"op": "cancel", "rid": rid})
            except rpc.RpcError:
                pass
        return True

    # -------------------------------------------------------- routing
    def _chain_keys(self, tokens):
        """The prompt's full-block chain hashes, shortest prefix
        first — the same rolling hash PrefixCache builds, so "the
        replica that served this chain" is exactly "the replica
        whose cache likely holds these blocks"."""
        bs = self.block_size
        keys, key = [], _SEED
        for b in range((len(tokens) - 1) // bs):
            key = hash((key,) + tuple(tokens[b * bs:(b + 1) * bs]))
            keys.append(key)
        return keys

    def _pick(self, req, exclude=()):
        """Choose a usable link: longest prefix-affinity match
        first, else least-queued (EWMA latency as tiebreak)."""
        now = time.monotonic()
        usable = {n: l for n, l in self._links.items()
                  if n not in exclude and l.usable(now)}
        if not usable:
            return None
        keys = self._chain_keys(req.prompt)
        for key in reversed(keys):
            name = self._affinity.get(key)
            if name in usable:
                return usable[name]
        return min(usable.values(),
                   key=lambda l: (len(l.inflight), l.ewma_latency))

    def _remember_affinity(self, req, link):
        for key in self._chain_keys(req.prompt):
            self._affinity[key] = link.name
        while len(self._affinity) > _AFFINITY_CAP:
            self._affinity.pop(next(iter(self._affinity)))

    def _entry_for(self, req, now):
        """The submit frame body: snapshot-entry schema (the same
        one ``ServingEngine.resubmit`` consumes) with deadlines as
        REMAINING seconds."""
        return {"op": "submit", "rid": req.id, "gen": req.gen,
                "prompt": req.prompt,
                "generated": list(req.generated),
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "ttft_done": req.ttft_done,
                "ttft_remaining_s": (
                    req.ttft_deadline_ts - now
                    if req.ttft_deadline_ts is not None
                    and not req.ttft_done else None),
                "deadline_remaining_s": (
                    req.deadline_ts - now
                    if req.deadline_ts is not None else None)}

    def _dispatch(self, req, exclude=()):
        """Send ``req`` to a usable replica (lock held).  No usable
        replica parks it on the pending list — poll() retries until
        a link heals or the deadline net expires it; an admitted
        request is never silently dropped."""
        link = self._pick(req, exclude=exclude)
        if link is None:
            if req not in self._pending:
                self._pending.append(req)
            return False
        now = time.monotonic()
        req.link = link.name
        link.inflight.add(req.id)
        if link.breaker.state == "half_open" \
                and link.breaker.probe_rid is None:
            link.breaker.probe_rid = req.id
            tracing.trace_event("router_breaker", replica=link.name,
                                state="half_open", rid=req.id)
        budget = (req.deadline_ts - now
                  if req.deadline_ts is not None else 0.0)
        try:
            link.send(self._entry_for(req, now),
                      budget=max(budget, 0.0))
        except rpc.RpcError as e:
            link.inflight.discard(req.id)
            self._fail_link_dispatch(link, f"dispatch send: {e}")
            return self._dispatch(req, exclude=tuple(exclude)
                                  + (link.name,))
        event = ("router_redispatch" if req.redispatches
                 else "router_dispatch")
        tracing.trace_event(event, rid=req.id, replica=link.name,
                            gen=req.gen,
                            generated=len(req.generated))
        self._remember_affinity(req, link)
        return True

    # ------------------------------------------------ failure handling
    def _fail_link_dispatch(self, link, why):
        """Count one dispatch failure against a link's breaker."""
        now = time.monotonic()
        _m_rep_fail.inc()
        if link.breaker.fail(now):
            _m_breaker_open.inc()
            tracing.trace_event("router_breaker", replica=link.name,
                                state="open", why=why)
        logger.warning("router: %s dispatch failure: %s", link.name,
                       why)

    def _on_link_down(self, link, why):
        """A replica stopped answering (reader EOF, frame
        corruption, failed send, staleness): re-dispatch everything
        it owned to survivors with remaining budgets, then let the
        background reconnect try to bring it back."""
        with self._lock:
            if not link.alive:
                return
            link.alive = False
            link.client.close()
            down_ts = time.monotonic()
            owned = [self._live[rid] for rid in list(link.inflight)
                     if rid in self._live]
            link.inflight.clear()
            self._fail_link_dispatch(link, why)
            tracing.trace_event("router_replica_down",
                                replica=link.name, why=why,
                                inflight=len(owned))
            for req in owned:
                if req.done:
                    continue
                req.gen += 1
                req.redispatches += 1
                req._redispatch_ts = down_ts
                _m_redispatch.inc()
                self._dispatch(req, exclude=(link.name,))
            self._update_health_gauge()
        if not self._draining:
            self._start_reconnect(link)

    def _start_reconnect(self, link):
        with self._lock:
            if link._reconnecting or self._closed.is_set():
                return
            link._reconnecting = True

        def _reconnect():
            try:
                # full jitter: N links re-homing after the same blip
                # must not retry in lockstep
                # deadline-ok: each jittered attempt arms a bounded
                # connect timeout (RpcClient.connect)
                link.connect(retry=True)
                logger.info("router: %s reconnected", link.name)
            except rpc.RpcError as e:
                logger.warning("router: %s reconnect failed: %s",
                               link.name, e)
            finally:
                link._reconnecting = False
                self._update_health_gauge()

        threading.Thread(target=_reconnect, daemon=True,
                         name=f"router-reconnect-{link.name}"
                         ).start()

    # ------------------------------------------------- frame handling
    def _on_frame(self, link, msg, budget):
        op = msg.get("op")
        if op == "pong":
            link.observe_pong(msg.get("seq"))
            return
        if op == "drained":
            with self._lock:
                link.drained = True
                self._drained_links.add(link.name)
            return
        if op == "stats":
            with self._lock:
                self._last_stats = msg
            return
        rid = msg.get("rid")
        if rid is None:
            return
        with self._lock:
            req = self._live.get(rid)
            if req is None or req.done:
                return                       # dup guard: already terminal
            if msg.get("gen", 0) != req.gen or \
                    req.link != link.name:
                return                       # stale dispatch generation
            if op == "token":
                tok = int(msg["tok"])
                req.generated.append(tok)
                if not req.ttft_done:
                    req.ttft_done = True
                    req.first_token_ts = time.monotonic()
                if req._redispatch_ts is not None:
                    _m_failover.observe(time.monotonic()
                                        - req._redispatch_ts)
                    req._redispatch_ts = None
                if link.breaker.probe_rid == rid:
                    link.breaker.ok()
                    tracing.trace_event("router_breaker",
                                        replica=link.name,
                                        state="closed", rid=rid)
                sink = req.sink
            elif op == "terminal":
                if link.breaker.probe_rid == rid:
                    link.breaker.ok()
                    tracing.trace_event("router_breaker",
                                        replica=link.name,
                                        state="closed", rid=rid)
                self._finalize(req, msg.get("state", FAILED),
                               tokens=msg.get("tokens"),
                               error=msg.get("error"), link=link)
                return
            elif op == "nack":
                probe_failed = link.breaker.probe_rid == rid
                link.inflight.discard(rid)
                self._fail_link_dispatch(
                    link, f"nack: {msg.get('error')}")
                if probe_failed:
                    tracing.trace_event("router_breaker",
                                        replica=link.name,
                                        state="reopened", rid=rid)
                if msg.get("fatal"):
                    self._finalize(req, FAILED,
                                   error=msg.get("error"),
                                   link=link)
                else:
                    req.gen += 1
                    req.redispatches += 1
                    _m_redispatch.inc()
                    self._dispatch(req, exclude=(link.name,))
                return
            else:
                return
        # token streaming to a front-door client happens outside the
        # lock (socket sends must not serialize the router)
        if op == "token" and sink is not None and not sink.closed:
            try:
                sink.send({"op": "token", "rid": rid, "tok": tok})
            except rpc.RpcError:
                pass

    def _finalize(self, req, state, tokens=None, error=None,
                  link=None):
        """The router's single terminal point: first caller wins,
        every other source of a terminal for this request is
        dropped at the ``req.done`` / ``_terminal_ids`` guard."""
        with self._lock:
            if req.done or req.id in self._terminal_ids:
                return False
            if state not in TERMINAL_STATES:
                state = FAILED
            if tokens is not None:
                req.generated = [int(t) for t in tokens]
            req.state = state
            req.error = error
            self._terminal_ids.add(req.id)
            self._live.pop(req.id, None)
            if req in self._pending:
                self._pending.remove(req)
            owner = self._links.get(req.link)
            if owner is not None:
                owner.inflight.discard(req.id)
            sink = req.sink
        tracing.trace_event("router_terminal", rid=req.id,
                            replica=req.link, state=state,
                            redispatches=req.redispatches)
        req.done_event.set()
        if sink is not None and not sink.closed:
            try:
                sink.send({"op": "terminal", "rid": req.id,
                           "state": state, "error": error,
                           "tokens": list(req.generated)})
            except rpc.RpcError:
                pass
        return True

    # ------------------------------------------------------ health
    def _update_health_gauge(self):
        now = time.monotonic()
        _m_healthy.set(sum(1 for l in self._links.values()
                           if l.healthy(now)))

    def poll(self):
        """One health tick: ping links, detect staleness, retry
        parked requests, run the deadline net, execute a
        signal-requested drain.  Call it from your serve loop (or
        rely on :meth:`listen`'s poller)."""
        now = time.monotonic()
        if self._drain_requested:
            self._drain_requested = False
            self.drain(wait=False)
        if now - self._last_ping >= self.ping_interval:
            self._last_ping = now
            for link in self._links.values():
                if link.alive:
                    link.ping()
        for link in list(self._links.values()):
            if link.alive and \
                    now - link.last_heard > self.stale_after:
                self._on_link_down(link, "heartbeat stale")
            elif not link.alive and not self._draining:
                # a link that is down — or was never reachable at
                # startup (replica still booting) — keeps getting
                # background reconnect attempts; _start_reconnect
                # dedups concurrent ones
                self._start_reconnect(link)
        with self._lock:
            pending, self._pending = self._pending, []
            for req in pending:
                if not req.done:
                    self._dispatch(req)
            # deadline net: a request past its total deadline whose
            # owner never delivered a terminal (wedged replica,
            # injected hang) expires HERE — exactly-one-terminal
            # must not depend on every replica behaving
            expired = [r for r in self._live.values()
                       if not r.done and r.deadline_ts is not None
                       and now > r.deadline_ts + self.expiry_grace]
        for req in expired:
            self._finalize(req, EXPIRED,
                           error="deadline exceeded (router net)")
        self._update_health_gauge()

    # -------------------------------------------------------- waiting
    def wait(self, reqs=None, timeout=30.0):
        """Drive :meth:`poll` until every request (default: all
        live) is terminal; returns True when they all are."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                targets = (list(self._live.values())
                           if reqs is None else reqs)
                if all(r.done for r in targets):
                    return True
            self.poll()
            time.sleep(self.poll_interval)
        with self._lock:
            targets = (list(self._live.values())
                       if reqs is None else reqs)
            return all(r.done for r in targets)

    # ---------------------------------------------------------- drain
    def drain(self, wait=True, timeout=None, snapshot_dir=None):
        """Stop admission and drain the fleet: every replica
        snapshots its in-flight requests (restorable via
        ``ServingEngine.restore``) and finishes its running batch.
        Returns the set of replicas that confirmed ``drained``."""
        with self._lock:
            # _draining only gates admission (the SIGTERM handler
            # sets it from the signal frame to shut the door
            # immediately); _drain_started tracks whether the drain
            # frames went out, so the latched drain still sends them
            first = not self._drain_started
            self._drain_started = True
            self._draining = True
            if snapshot_dir is not None:
                self.snapshot_dir = snapshot_dir
        if first:
            tracing.trace_event("router_drain",
                                replicas=len(self._links))
            for link in self._links.values():
                if not link.alive:
                    continue
                snap = None
                if self.snapshot_dir:
                    snap = os.path.join(self.snapshot_dir,
                                        f"{link.name}.snap")
                try:
                    link.send({"op": "drain", "snapshot": snap})
                except rpc.RpcError:
                    pass
        if wait:
            t = rpc.default_timeout() if timeout is None else timeout
            deadline = time.monotonic() + t
            while time.monotonic() < deadline:
                with self._lock:
                    alive = {n for n, l in self._links.items()
                             if l.alive}
                    if alive <= self._drained_links:
                        break
                time.sleep(self.poll_interval)
        with self._lock:
            return set(self._drained_links)

    def install_sigterm(self, snapshot_dir=None):
        """SIGTERM -> fleet drain.  The handler only *latches* the
        request (socket work from a signal frame is asking for
        re-entrancy trouble); the next :meth:`poll` performs the
        drain.  Main-thread only; returns False when it cannot
        install."""
        import signal as _signal
        if threading.current_thread() is not \
                threading.main_thread():
            return False
        if snapshot_dir is not None:
            self.snapshot_dir = snapshot_dir
        prev = _signal.getsignal(_signal.SIGTERM)

        def _handler(signum, frame):
            self._drain_requested = True
            self._draining = True
            if callable(prev):
                prev(signum, frame)

        _signal.signal(_signal.SIGTERM, _handler)
        return True

    def replica_stats(self, name, timeout=5.0):
        """Ask one replica for its engine stats + block-pool audit
        (the per-replica ``BlockPool.live()`` leak check)."""
        link = self._links[name]
        with self._lock:
            self._last_stats = None
        link.send({"op": "stats"})
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                s = self._last_stats
            if s is not None and s.get("replica"):
                return s
            time.sleep(0.01)
        raise rpc.RpcTimeoutError(
            f"replica {name} stats did not arrive in {timeout}s")

    # ---------------------------------------------------------- stats
    def stats(self):
        now = time.monotonic()
        with self._lock:
            return {
                "live": len(self._live),
                "pending": len(self._pending),
                "terminals": len(self._terminal_ids),
                "draining": self._draining,
                "replicas": {
                    n: {"alive": l.alive,
                        "healthy": l.healthy(now),
                        "inflight": len(l.inflight),
                        "breaker": l.breaker.state,
                        "ewma_latency_s": l.ewma_latency,
                        "drained": l.drained}
                    for n, l in self._links.items()},
            }

    # ----------------------------------------------------- front door
    def listen(self, host="127.0.0.1", port=None,
               poll_in_background=True):
        """Expose the router over the same frame protocol clients of
        a single replica would speak (``MXTPU_ROUTER_PORT``):
        ``submit`` admits (reply ``ack`` or ``reject``) and streams
        ``token``/``terminal`` frames back on the submitting
        connection; ``cancel``, ``stats``, ``ping`` and ``drain``
        map to the same-named methods.  Returns the bound port."""
        if port is None:
            port = get_env("MXTPU_ROUTER_PORT")

        def _handler(msg, conn, budget):
            op = msg.get("op")
            if op == "ping":
                return {"op": "pong", "seq": msg.get("seq")}
            if op == "stats":
                return {"op": "stats", "stats": self.stats()}
            if op == "cancel":
                self.cancel(int(msg["rid"]))
                return None
            if op == "drain":
                self.drain(wait=False)
                return {"op": "draining"}
            if op == "submit":
                try:
                    req = self.submit(
                        msg["prompt"], msg["max_new_tokens"],
                        eos_id=msg.get("eos_id"),
                        ttft_deadline=msg.get("ttft_deadline"),
                        deadline=(budget if budget and budget > 0
                                  else msg.get("deadline")))
                except ServeRejectedError as e:
                    return {"op": "reject", "error": str(e)}
                req.sink = conn
                return {"op": "ack", "rid": req.id}
            return {"op": "error", "error": f"unknown op {op!r}"}

        self._frontend = rpc.RpcServer(_handler, host=host,
                                       port=port,
                                       name="router-frontend")
        self._frontend.start()
        # live introspection: router statusz mirrors the stats op
        # (same host-side snapshot, no request-path involvement)
        debugz.maybe_start("router")
        debugz.register_provider("router", self.stats)
        if poll_in_background:
            def _poll_loop():
                while not self._closed.is_set():
                    self.poll()
                    time.sleep(self.poll_interval)

            self._poller = threading.Thread(
                target=_poll_loop, daemon=True,
                name="router-poller")
            self._poller.start()
        return self._frontend.port
