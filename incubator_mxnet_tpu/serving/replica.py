"""Fleet replica: one :class:`ServingEngine` behind the RPC server.

A replica is a worker process the router dispatches requests to over
serving/rpc.py frames.  It speaks launch.py's heartbeat files
(``resilience.start_heartbeat``), honors SIGTERM as
snapshot-then-drain (``ServingEngine.install_sigterm``), and streams
every token plus exactly one terminal status frame back per request
id.  Protocol (all frames JSON dicts with an ``op`` field):

router -> replica
    ``submit``   one request in ``_snapshot_request`` entry form plus
                 ``rid`` (the fleet-wide id — used as the engine id)
                 and ``gen`` (dispatch generation for dedup)
    ``cancel``   cancel ``rid``
    ``drain``    snapshot (to ``snapshot`` path or the configured
                 one), latch drain, reply ``drained`` once the
                 running batch finishes
    ``stats``    reply with engine stats + the per-replica
                 ``BlockPool.live()`` audit
    ``ping``     liveness probe, replied to on the RPC reader thread
                 (stays responsive even while the engine loop works)

replica -> router
    ``token``     one generated token for ``rid`` (tagged ``gen``)
    ``terminal``  the request's single terminal state, with the full
                  generated token list (authoritative for dedup)
    ``nack``      a dispatch this replica could not accept
                  (``fatal`` tells the router whether to re-route or
                  fail the request)

Dispatch generations make re-dispatch safe: the router bumps ``gen``
each time it re-homes a request, and both sides drop frames from a
stale generation — a request re-dispatched *back* to this replica
after a network blip cancels the old engine copy first and defers
the resubmit until that copy's (swallowed) terminal confirms its
blocks are free, so exactly one copy ever decodes.

Deterministic fault injection: each inbound dispatch consults
``router:replica`` (``MXTPU_FAULT_SPEC``) — ``kill`` hard-exits the
process (the failover test vector), ``hang`` wedges the serve loop
(the router's deadline net catches it), ``error`` nacks the dispatch
(a breaker failure without process death).
"""
import argparse
import os
import sys
import threading
import time
from collections import deque

from .. import debugz, resilience, telemetry, tracing
from ..utils.env import get_env
from ..utils.log import get_logger
from . import rpc
from .engine import ServingEngine
from .scheduler import RequestTooLargeError

logger = get_logger("serving.replica")

_m_dispatches = telemetry.counter("fleet_dispatches_total")
_m_terminals = telemetry.counter("fleet_terminals_total")
_m_nacks = telemetry.counter("fleet_nacks_total")


class ReplicaServer:
    """Wrap one engine behind the frame protocol (see module doc)."""

    def __init__(self, model=None, engine=None, name=None,
                 host="127.0.0.1", port=None, snapshot_path=None,
                 poll=0.002, **engine_kw):
        if engine is None:
            engine = ServingEngine(model, **engine_kw)
        self.eng = engine
        self.name = name or f"replica-{os.getpid()}"
        self.snapshot_path = snapshot_path
        self._poll = poll
        self._inbox = deque()       # (msg, conn) pairs, reader -> loop
        self._router = None         # conn terminals/tokens stream to
        self._stop = threading.Event()
        self._gen = {}              # fleet rid -> current dispatch gen
        self._stale = set()         # rids whose engine copy is superseded
        self._deferred = {}         # rid -> submit msg awaiting old copy
        if port is None:
            port = get_env("MXTPU_REPLICA_PORT")
        self._srv = rpc.RpcServer(self._on_frame, host=host,
                                  port=port, name=self.name)

    @property
    def port(self):
        return self._srv.port

    # ------------------------------------------------ RPC reader side
    def _on_frame(self, msg, conn, budget):
        op = msg.get("op")
        if op in ("submit", "cancel", "drain"):
            # only command frames claim the streaming conn: a stats
            # probe from a side channel must not steal the router's
            # token stream
            self._router = conn
        if op == "ping":
            # replied inline on the reader thread: liveness must not
            # queue behind engine work
            return {"op": "pong", "seq": msg.get("seq"),
                    "replica": self.name,
                    "queue_depth": len(self.eng._sched.waiting),
                    "running": self.eng._sched.n_running()}
        self._inbox.append((msg, conn, budget))
        return None

    # ------------------------------------------------ serve-loop side
    def _send(self, msg, budget=0.0):
        """Best-effort stream to the router: a dead link drops the
        frame (the router re-dispatches everything this replica owned
        once it notices — state lives above the transport)."""
        conn = self._router
        if conn is None or conn.closed:
            return False
        try:
            conn.send(msg, budget=budget)
            return True
        except rpc.RpcError:
            return False

    def _handle_submit(self, msg, conn, budget):
        rid = int(msg["rid"])
        gen = int(msg.get("gen", 0))
        self._gen[rid] = gen
        try:
            resilience.inject("router", "replica")
        except resilience.TransientError as e:
            _m_nacks.inc()
            self._send({"op": "nack", "rid": rid, "gen": gen,
                        "replica": self.name, "error": str(e),
                        "fatal": False})
            return
        live = self.eng._live.get(rid)
        if live is not None and not live.done:
            # the same fleet request re-dispatched back here (net
            # blip): cancel the old engine copy and defer this
            # submit until its swallowed terminal frees its blocks —
            # exactly one copy may decode
            self._stale.add(rid)
            self._deferred[rid] = msg
            self.eng.cancel(rid)
            return
        self._stale.discard(rid)
        entry = {"id": rid, "prompt": msg["prompt"],
                 "generated": msg.get("generated", []),
                 "max_new_tokens": msg["max_new_tokens"],
                 "eos_id": msg.get("eos_id"),
                 "ttft_done": msg.get("ttft_done", False),
                 "ttft_remaining_s": msg.get("ttft_remaining_s"),
                 "deadline_remaining_s": (
                     budget if budget and budget > 0
                     else msg.get("deadline_remaining_s")),
                 "preemptions": int(msg.get("preemptions", 0))}
        try:
            req = self.eng.resubmit(
                entry, redispatch=bool(msg.get("generated")))
        except RequestTooLargeError as e:
            _m_nacks.inc()
            self._send({"op": "nack", "rid": rid, "gen": gen,
                        "replica": self.name, "error": str(e),
                        "fatal": True})
            return
        _m_dispatches.inc()
        tracing.trace_event("fleet_dispatch", rid=rid,
                            replica=self.name, gen=gen,
                            generated=len(req.generated))

    def _handle(self, msg, conn, budget):
        op = msg.get("op")
        if op == "submit":
            self._handle_submit(msg, conn, budget)
        elif op == "cancel":
            self.eng.cancel(int(msg["rid"]))
        elif op == "drain":
            path = msg.get("snapshot") or self.snapshot_path
            if path:
                self.eng.snapshot(path)
            self.eng._latch_drain()
        elif op == "stats":
            reply = {"op": "stats", "replica": self.name,
                     "stats": self.eng.stats(),
                     "pool_live": {str(k): v for k, v in
                                   self.eng.pool.live().items()},
                     "num_allocated": self.eng.pool.num_allocated}
            try:
                conn.send(reply)
            except rpc.RpcError:
                pass
        else:
            logger.warning("%s: unknown op %r dropped", self.name,
                           op)

    def _forward_terminal(self, req):
        rid = req.id
        if rid in self._stale:
            # superseded copy: swallow its terminal (the fleet-wide
            # terminal belongs to the live dispatch) and admit any
            # deferred resubmit now that its blocks are free
            self._stale.discard(rid)
            deferred = self._deferred.pop(rid, None)
            if deferred is not None:
                self._handle_submit(deferred, self._router, 0.0)
            return
        gen = self._gen.pop(rid, 0)
        _m_terminals.inc()
        tracing.trace_event("fleet_terminal", rid=rid,
                            replica=self.name, gen=gen,
                            state=req.state)
        self._send({"op": "terminal", "rid": rid, "gen": gen,
                    "replica": self.name, "state": req.state,
                    "error": (str(req.error)
                              if req.error is not None else None),
                    "tokens": [int(t) for t in req.generated]})

    def serve_forever(self):
        """Run until drained (SIGTERM or a ``drain`` frame) or
        :meth:`stop`.  Installs the SIGTERM snapshot-then-drain hook
        when a snapshot path is configured (main thread only — a
        loop driven from elsewhere keeps the previous disposition)."""
        resilience.start_heartbeat()
        if self.snapshot_path:
            self.eng.install_sigterm(self.snapshot_path, drain=True)
        self._srv.start()
        eng = self.eng
        # live introspection: statusz serves engine stats + scheduler
        # depth (host-side counters only — no step-loop interference)
        debugz.maybe_start("replica")
        unregister = debugz.register_provider(
            "engine", lambda: {
                "name": self.name,
                "stats": eng.stats(),
                "queue_depth": len(eng._sched.waiting),
                "running": eng._sched.n_running(),
                "draining": eng._draining,
            })
        try:
            while not self._stop.is_set():
                busy = False
                while self._inbox:
                    self._handle(*self._inbox.popleft())
                    busy = True
                if eng.has_work():
                    for req, tok in eng.step():
                        rid = req.id
                        if rid in self._stale:
                            continue
                        self._send({"op": "token", "rid": rid,
                                    "gen": self._gen.get(rid, 0),
                                    "replica": self.name,
                                    "tok": int(tok)})
                    busy = True
                for req in eng.take_completed():
                    self._forward_terminal(req)
                    busy = True
                if eng._draining and not eng.has_work() \
                        and not self._inbox:
                    self._send({"op": "drained",
                                "replica": self.name,
                                "snapshot": self.snapshot_path})
                    break
                if not busy:
                    time.sleep(self._poll)
        finally:
            unregister()
            self._srv.close()
            resilience.stop_heartbeat()

    def stop(self):
        self._stop.set()

    def close(self):
        self.stop()
        self._srv.close()


def _build_tiny(spec):
    """Deterministic tiny TransformerLM for fleet tests/benches:
    fixed seed + Xavier init means every process that builds the
    same spec holds bitwise-identical weights — which is what makes
    re-dispatched outputs token-identical across replicas."""
    import incubator_mxnet_tpu as mx
    from ..gluon.model_zoo.transformer import TransformerLM
    kw = {"vocab": 37, "d_model": 32, "n_layers": 2, "n_heads": 4,
          "max_len": 64}
    for part in (spec or "").split(","):
        part = part.strip()
        if part:
            k, v = part.split("=")
            kw[k.strip()] = int(v)
    mx.random.seed(0)
    net = TransformerLM(kw["vocab"], d_model=kw["d_model"],
                        n_layers=kw["n_layers"],
                        n_heads=kw["n_heads"],
                        max_len=kw["max_len"])
    net.initialize(mx.init.Xavier())
    return net


def main(argv=None):
    """CLI entry: ``python -m incubator_mxnet_tpu.serving.replica``.
    Builds the deterministic tiny model (``--tiny``), optionally
    restores a drain snapshot, and serves until drained."""
    ap = argparse.ArgumentParser(prog="serving.replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--port-file", default=None,
                    help="write the bound port here once listening")
    ap.add_argument("--name", default=None)
    ap.add_argument("--tiny", default="",
                    help="tiny-model spec, e.g. 'vocab=37,d_model=32'")
    ap.add_argument("--snapshot", default=None,
                    help="SIGTERM/drain snapshot path")
    ap.add_argument("--restore", default=None,
                    help="restore this snapshot at boot")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--block-size", type=int, default=None)
    ap.add_argument("--num-blocks", type=int, default=None)
    ap.add_argument("--prefix-cache", type=int, default=None)
    args = ap.parse_args(argv)
    net = _build_tiny(args.tiny)
    eng_kw = {}
    for key in ("max_batch", "block_size", "num_blocks"):
        if getattr(args, key) is not None:
            eng_kw[key] = getattr(args, key)
    if args.prefix_cache is not None:
        eng_kw["prefix_cache"] = bool(args.prefix_cache)
    if args.restore and os.path.exists(args.restore):
        engine = ServingEngine.restore(net, args.restore, **eng_kw)
        srv = ReplicaServer(engine=engine, name=args.name,
                            host=args.host, port=args.port,
                            snapshot_path=args.snapshot)
    else:
        srv = ReplicaServer(net, name=args.name, host=args.host,
                            port=args.port,
                            snapshot_path=args.snapshot, **eng_kw)
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(srv.port))
        os.replace(tmp, args.port_file)
    logger.info("%s listening on %s:%d", srv.name, args.host,
                srv.port)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
