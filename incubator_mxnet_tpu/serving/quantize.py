"""int8 weight quantization for serving density.

Serving throughput on a memory-bound accelerator is set by how many
weight bytes stream per decode step; int8 storage with per-output-
channel fp32 scales cuts that ~4x versus fp32 at <0.5% logit error
for trained transformer weights (symmetric absmax quantization, the
standard W8 recipe).

The representation keeps the weight pytree's SHAPE: every 2D float
matrix in a ``TransformerLM._decode_weights()`` tree (qkv / proj /
up / down projections, the tied head, and the embedding tables)
becomes ``{"q": int8 (out, in), "s": float32 (out,)}``; biases,
LayerNorm affines, and stacked 3D MoE expert weights stay fp32.  The
paged prefill/step builders (gluon/model_zoo/transformer.py) detect
the dict leaves at trace time and dequantize at use — matmul weights
as ``q.astype(f32) * s[:, None]`` inside the jit (XLA fuses the
dequant into the matmul read), embedding tables per GATHERED row
only, so a decode step never materializes a dense fp32 table.

``quantize_weights`` validates nothing by itself; the serving bench
and tests/test_serving.py compare int8 vs fp32 logits end-to-end.
"""

__all__ = ["quantize_weights", "quantization_error",
           "weights_nbytes"]


def _q2d(w):
    """Symmetric absmax int8 per output channel (axis 0)."""
    import jax.numpy as jnp
    amax = jnp.max(jnp.abs(w), axis=1)
    s = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / s[:, None]), -127, 127) \
        .astype(jnp.int8)
    return {"q": q, "s": s}


def quantize_weights(wts, include_embeddings=True):
    """Quantize a ``TransformerLM._decode_weights()`` pytree to int8.

    Returns a NEW tree (the input is untouched) with every dense 2D
    projection replaced by ``{"q", "s"}`` pairs.  With
    ``include_embeddings=False`` the token/position tables stay fp32
    (their gathers are cheap; quantizing them trades a little logit
    accuracy for the largest single density win on big vocabs)."""
    out = {"ln_f": wts["ln_f"], "layers": []}
    if include_embeddings:
        out["embed"] = _q2d(wts["embed"])
        if "pos" in wts:
            out["pos"] = _q2d(wts["pos"])
    else:
        out["embed"] = wts["embed"]
        if "pos" in wts:
            out["pos"] = wts["pos"]
    out["head"] = _q2d(wts["head"])
    for lw in wts["layers"]:
        nl = dict(ln1=lw["ln1"], ln2=lw["ln2"],
                  qkv=(_q2d(lw["qkv"][0]), lw["qkv"][1]),
                  proj=(_q2d(lw["proj"][0]), lw["proj"][1]))
        if "moe" in lw:
            # stacked (E, H, D) expert weights keep fp32: per-expert
            # per-channel scales would need a 3D scale plan — out of
            # scope for the density this tier targets
            nl["moe"] = lw["moe"]
        else:
            nl["up"] = (_q2d(lw["up"][0]), lw["up"][1])
            nl["down"] = (_q2d(lw["down"][0]), lw["down"][1])
        out["layers"].append(nl)
    return out


def quantization_error(wts, qwts):
    """Max relative reconstruction error over quantized matrices —
    a cheap sanity probe (the real acceptance is logit-level)."""
    import jax.numpy as jnp

    def leaf_err(w, q):
        deq = q["q"].astype(jnp.float32) * q["s"][:, None]
        denom = jnp.maximum(jnp.max(jnp.abs(w)), 1e-12)
        return float(jnp.max(jnp.abs(deq - w)) / denom)

    errs = []

    def walk(a, b):
        if isinstance(b, dict) and set(b) == {"q", "s"}:
            errs.append(leaf_err(a, b))
        elif isinstance(b, dict):
            for k in b:
                walk(a[k], b[k])
        elif isinstance(b, (list, tuple)):
            for x, y in zip(a, b):
                walk(x, y)

    walk(wts, qwts)
    return max(errs) if errs else 0.0


def weights_nbytes(wts):
    """Total bytes of every array leaf (int8 payloads + scales
    included) — the density number the bench reports."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(wts):
        total += leaf.size * leaf.dtype.itemsize
    return int(total)
