"""Fixed device-resident KV block pool + host-side allocator.

The serving tier's memory model (docs/serving.md): instead of one
dense ``(max_len, ...)`` KV buffer per sequence, every layer owns ONE
pool array of shape ``(num_blocks, block_size, kv_heads, head_dim)``
and each running request holds an ordered list of block ids — its
*block table*.  A sequence of ``n`` tokens costs ``ceil(n /
block_size)`` blocks at its ACTUAL length, so thousands of mixed-
length sequences share HBM with at most ``block_size - 1`` wasted
slots each, and a shared prompt prefix is one set of block ids held
by many tables (prefix caching, cache_manager.py).

:class:`BlockPool` is the host-side allocator over that id space:
a free stack plus a per-block refcount.  Refcounting is what makes
prefix sharing copy-free — a block lives until its last holder
(request or prefix cache) releases it, and a double ``free`` raises
instead of silently corrupting another request's context.

Block id 0 is RESERVED as the scratch block: inactive batch slots
and padded prefill rows scatter their garbage writes there inside
the jitted step, so the compiled kernel never needs a host-side
branch on slot liveness.  The allocator never hands out id 0.
"""

__all__ = ["BlockPool", "BlockPoolExhausted"]


class BlockPoolExhausted(RuntimeError):
    """No free blocks left in the pool.

    The scheduler answers this by evicting unreferenced prefix-cache
    blocks and, failing that, preempting the latest-admitted request
    (its blocks free, it re-queues) — see engine._grow."""


class BlockPool:
    """Allocator for a fixed pool of ``num_blocks`` KV blocks of
    ``block_size`` tokens each.  Block 0 is the reserved scratch
    block and is never allocated; capacity is ``num_blocks - 1``.

    All methods are host-side and O(blocks touched); the device pool
    arrays themselves live in the engine — this class only governs
    which ids are live and how many holders each has.
    """

    def __init__(self, num_blocks, block_size):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (got {num_blocks}): block "
                "0 is the reserved scratch block")
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1 (got {block_size})")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free stack: recently-freed blocks are re-used first
        # (their pool slots are warm in cache on-device)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = {}                    # live block id -> refcount

    # ------------------------------------------------------- queries
    @property
    def capacity(self):
        """Allocatable blocks (scratch block excluded)."""
        return self.num_blocks - 1

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_allocated(self):
        return self.capacity - len(self._free)

    def utilization(self):
        """Fraction of the allocatable pool currently live."""
        return self.num_allocated / self.capacity

    def refcount(self, block_id):
        """Current holders of ``block_id`` (0 when free)."""
        return self._ref.get(block_id, 0)

    def live(self):
        """Snapshot of live block refcounts ``{block_id: holders}``.

        The leak-audit view: after an engine drains, every live
        block must be accounted for by the prefix cache alone — the
        chaos/regression tests assert exactly that, so a terminal
        path (retire/evict/expire/cancel) that forgets to free shows
        up as a named block with a holder nobody owns."""
        return dict(self._ref)

    # ----------------------------------------------------- lifecycle
    def alloc(self, n=1):
        """Allocate ``n`` blocks at refcount 1; returns their ids.

        All-or-nothing: raises :class:`BlockPoolExhausted` (and
        allocates nothing) when fewer than ``n`` are free, so a
        failed admission never leaks a partial allocation."""
        if n < 0:
            raise ValueError(f"alloc(n={n})")
        if n > len(self._free):
            raise BlockPoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"(pool capacity {self.capacity})")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, block_ids):
        """Add a holder to each live block (prefix-cache hits and
        inserts).  Incref of a free block is always a bug."""
        for b in block_ids:
            if b not in self._ref:
                raise ValueError(
                    f"incref on free block {b}: a holder must exist "
                    "before it can be shared")
            self._ref[b] += 1

    def free(self, block_ids):
        """Drop one holder from each block; a block whose last
        holder leaves returns to the free stack.  Freeing an
        already-free block raises (double-free)."""
        for b in block_ids:
            r = self._ref.get(b)
            if r is None:
                raise ValueError(
                    f"double free of block {b} (already free)")
            if r == 1:
                del self._ref[b]
                self._free.append(b)
            else:
                self._ref[b] = r - 1

    def __repr__(self):
        return (f"BlockPool(blocks={self.num_blocks}, "
                f"block_size={self.block_size}, "
                f"free={self.num_free}/{self.capacity})")
