"""Continuous-batching scheduler state: requests, slots, queue.

Policy (docs/serving.md):

- **FIFO admission, prefill-prioritized.**  Every engine iteration
  first fills free batch slots from the waiting queue (one prefill
  per admission), then runs ONE decode step for the whole batch —
  so a new request's first token never waits behind an entire
  stream's decode, and decode throughput is only briefly traded for
  time-to-first-token.
- **Preemption by block exhaustion.**  When a running sequence needs
  its next KV block and the pool (after prefix-cache eviction) has
  none, the LATEST-admitted running request is preempted: its blocks
  free immediately, and it re-queues at the FRONT with its generated
  tokens intact.  Re-admission re-prefills prompt+generated — with
  the prefix cache warm this is usually a cheap suffix prefill — and
  greedy decoding makes the recompute exact, so preemption is
  invisible in the output stream.
- **Retirement on the spot.**  A request that emits its last token
  (budget or EOS) frees its blocks in the same iteration, so the
  next iteration's admissions see the memory.

The scheduler is pure host-side bookkeeping; device state (pools,
compiled steps) lives in engine.py.
"""
import time
from collections import deque

__all__ = ["Request", "Scheduler", "SchedulingError",
           "QUEUED", "RUNNING", "FINISHED", "FAILED"]

QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
FAILED = "failed"


class SchedulingError(RuntimeError):
    """The schedule cannot make progress (e.g. a single request
    needs more blocks than the whole pool holds)."""


class Request:
    """One generation request flowing through the engine.

    ``prompt`` is immutable; ``generated`` accumulates emitted
    tokens (and survives preemption — re-admission prefills
    ``prompt + generated``).  Timing fields are host monotonic
    stamps feeding the queue-wait / TTFT / per-token histograms.
    """

    __slots__ = ("id", "prompt", "max_new_tokens", "eos_id", "state",
                 "generated", "block_ids", "n_past", "slot",
                 "admit_seq", "preemptions", "error", "logits",
                 "submit_ts", "admit_ts", "first_token_ts",
                 "last_token_ts", "finish_ts", "enqueue_ts",
                 "queue_wait_s", "prefill_s", "last_slot")

    def __init__(self, req_id, prompt, max_new_tokens, eos_id=None):
        self.id = req_id
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.state = QUEUED
        self.generated = []
        self.block_ids = []
        self.n_past = 0
        self.slot = None
        self.admit_seq = -1
        self.preemptions = 0
        self.error = None
        self.logits = None
        self.submit_ts = time.monotonic()
        self.admit_ts = None
        self.first_token_ts = None
        self.last_token_ts = None
        self.finish_ts = None
        # flight-recorder decomposition: time spent QUEUED (accrues
        # again after every preemption — enqueue_ts re-stamps) and
        # cumulative suffix-prefill wall time (re-prefills included)
        self.enqueue_ts = self.submit_ts
        self.queue_wait_s = 0.0
        self.prefill_s = 0.0
        # pinned at FIRST admission and never cleared: the profiler
        # places every phase of one request on one lane, so terminal
        # events (after clear() nulls .slot) and re-admissions into a
        # different slot keep rendering on the same track
        self.last_slot = None

    @property
    def done(self):
        return self.state in (FINISHED, FAILED)

    @property
    def tokens(self):
        """Full stream: prompt + generated so far."""
        return self.prompt + self.generated

    def __repr__(self):
        return (f"Request(id={self.id}, state={self.state}, "
                f"prompt={len(self.prompt)}t, "
                f"generated={len(self.generated)}/"
                f"{self.max_new_tokens})")


class Scheduler:
    """Waiting queue + fixed slot array for ``max_batch`` runners."""

    def __init__(self, max_batch):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1 ({max_batch})")
        self.max_batch = int(max_batch)
        self.slots = [None] * self.max_batch
        self.waiting = deque()
        self._admit_counter = 0

    # ------------------------------------------------------- queue
    def add(self, req):
        self.waiting.append(req)

    def push_front(self, req):
        """Re-queue at the head (preemption / failed admission)."""
        self.waiting.appendleft(req)

    def pop_waiting(self):
        return self.waiting.popleft() if self.waiting else None

    def has_waiting(self):
        return bool(self.waiting)

    # ------------------------------------------------------- slots
    def free_slot(self):
        """Index of a free slot, or None when the batch is full."""
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def place(self, req, slot):
        assert self.slots[slot] is None
        self.slots[slot] = req
        req.slot = slot
        if req.last_slot is None:
            req.last_slot = slot    # lane pin: first admission wins
        req.state = RUNNING
        req.admit_seq = self._admit_counter
        self._admit_counter += 1

    def clear(self, req):
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None

    def running(self):
        return [r for r in self.slots if r is not None]

    def n_running(self):
        return sum(1 for r in self.slots if r is not None)

    def any_running(self):
        return any(r is not None for r in self.slots)

    def latest_running(self):
        """Preemption victim: the most recently admitted runner."""
        live = self.running()
        return max(live, key=lambda r: r.admit_seq) if live else None

    def has_work(self):
        return bool(self.waiting) or self.any_running()
