"""Continuous-batching scheduler state: requests, slots, queue.

Policy (docs/serving.md):

- **FIFO admission, prefill-prioritized.**  Every engine iteration
  first fills free batch slots from the waiting queue (one prefill
  per admission), then runs ONE decode step for the whole batch —
  so a new request's first token never waits behind an entire
  stream's decode, and decode throughput is only briefly traded for
  time-to-first-token.
- **Preemption by block exhaustion.**  When a running sequence needs
  its next KV block and the pool (after prefix-cache eviction) has
  none, the LATEST-admitted running request is preempted: its blocks
  free immediately, and it re-queues at the FRONT with its generated
  tokens intact.  Re-admission re-prefills prompt+generated — with
  the prefix cache warm this is usually a cheap suffix prefill — and
  greedy decoding makes the recompute exact, so preemption is
  invisible in the output stream.
- **Retirement on the spot.**  A request that emits its last token
  (budget or EOS) frees its blocks in the same iteration, so the
  next iteration's admissions see the memory.

The scheduler is pure host-side bookkeeping; device state (pools,
compiled steps) lives in engine.py.
"""
import threading
import time
from collections import deque

__all__ = ["Request", "Scheduler", "ServingError", "SchedulingError",
           "ServeRejectedError", "RequestTooLargeError",
           "QUEUED", "RUNNING", "FINISHED", "FAILED", "EXPIRED",
           "CANCELLED", "TERMINAL_STATES"]

QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
FAILED = "failed"
# SLO/survival terminals (docs/serving.md "SLOs, shedding, drain"):
# a request whose ttft/total deadline passed before it could finish,
# and one the client cancelled (engine.cancel / abandoned stream).
# Both free their blocks and slot in the iteration that detects them.
EXPIRED = "expired"
CANCELLED = "cancelled"

TERMINAL_STATES = (FINISHED, FAILED, EXPIRED, CANCELLED)


class ServingError(RuntimeError):
    """Base class for serving-tier failures (typed so traffic code
    can tell the serving layer's own verdicts from model errors)."""


class SchedulingError(ServingError):
    """The schedule cannot make progress (e.g. a single request
    needs more blocks than the whole pool holds)."""


class ServeRejectedError(ServingError):
    """``submit()`` refused the request at admission control: the
    bounded wait queue (``MXTPU_SERVE_QUEUE_LIMIT``) or queued
    prompt-token budget (``MXTPU_SERVE_QUEUE_TOKENS``) is full, or
    the engine is draining.  Shedding at the door keeps admitted
    requests' latency bounded instead of letting the queue grow into
    unbounded TTFT collapse — callers should retry elsewhere/later."""


class RequestTooLargeError(ServingError, ValueError):
    """The request can never be served by this engine: its prompt +
    ``max_new_tokens`` exceeds the model context or needs more KV
    blocks than the whole pool holds.  Raised loudly at ``submit()``
    (and re-checked at admission for snapshot-restored requests)
    instead of leaving the request queued forever.  Also a
    ValueError so legacy size-validation handlers keep working."""


class Request:
    """One generation request flowing through the engine.

    ``prompt`` is immutable; ``generated`` accumulates emitted
    tokens (and survives preemption — re-admission prefills
    ``prompt + generated``).  Timing fields are host monotonic
    stamps feeding the queue-wait / TTFT / per-token histograms.
    """

    __slots__ = ("id", "prompt", "max_new_tokens", "eos_id", "state",
                 "generated", "block_ids", "n_past", "slot",
                 "admit_seq", "preemptions", "error", "logits",
                 "submit_ts", "admit_ts", "first_token_ts",
                 "last_token_ts", "finish_ts", "enqueue_ts",
                 "queue_wait_s", "prefill_s", "last_slot",
                 "ttft_deadline_ts", "deadline_ts",
                 "cancel_requested", "cancel_counted")

    def __init__(self, req_id, prompt, max_new_tokens, eos_id=None):
        self.id = req_id
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.state = QUEUED
        self.generated = []
        self.block_ids = []
        self.n_past = 0
        self.slot = None
        self.admit_seq = -1
        self.preemptions = 0
        self.error = None
        self.logits = None
        self.submit_ts = time.monotonic()
        self.admit_ts = None
        self.first_token_ts = None
        self.last_token_ts = None
        self.finish_ts = None
        # flight-recorder decomposition: time spent QUEUED (accrues
        # again after every preemption — enqueue_ts re-stamps) and
        # cumulative suffix-prefill wall time (re-prefills included)
        self.enqueue_ts = self.submit_ts
        self.queue_wait_s = 0.0
        self.prefill_s = 0.0
        # pinned at FIRST admission and never cleared: the profiler
        # places every phase of one request on one lane, so terminal
        # events (after clear() nulls .slot) and re-admissions into a
        # different slot keep rendering on the same track
        self.last_slot = None
        # SLO state: absolute MONOTONIC expiry stamps (None = no
        # deadline).  ttft_deadline_ts stops binding once the first
        # token lands (the stamp itself stays set — the engine's
        # armed-deadline accounting counts it until terminal);
        # deadline_ts bounds the whole request.  The engine's reap
        # sweep enforces both; snapshot/restore persists the
        # REMAINING seconds, never the stamps (a monotonic clock
        # does not survive the process).
        self.ttft_deadline_ts = None
        self.deadline_ts = None
        # set by engine.cancel() from any thread; honored (terminal
        # state CANCELLED, blocks freed) at the next engine
        # iteration.  cancel_counted marks a cancel that bumped the
        # engine's _cancels_pending counter — the lock-free
        # stream-abandon flag deliberately does NOT, and _finalize
        # must only release counts that were actually taken (an
        # uncounted decrement would starve another request's
        # pending cancel behind the reap gate)
        self.cancel_requested = False
        self.cancel_counted = False

    @property
    def done(self):
        return self.state in TERMINAL_STATES

    @property
    def tokens(self):
        """Full stream: prompt + generated so far."""
        return self.prompt + self.generated

    def __repr__(self):
        return (f"Request(id={self.id}, state={self.state}, "
                f"prompt={len(self.prompt)}t, "
                f"generated={len(self.generated)}/"
                f"{self.max_new_tokens})")


class Scheduler:
    """Waiting queue + fixed slot array for ``max_batch`` runners.

    ``queued_tokens`` tracks the summed token length (prompt +
    generated-so-far) of everything in the waiting queue — the
    admission controller's queued-prompt-token budget
    (``MXTPU_SERVE_QUEUE_TOKENS``) reads it without walking the
    queue on every ``submit()``.  Its updates take a private lock:
    client threads add (under the engine's submit lock) while the
    engine loop pops, and a lost read-modify-write would drift the
    counter for the rest of the process — shedding against a queue
    that is not actually full (or never shedding again)."""

    def __init__(self, max_batch):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1 ({max_batch})")
        self.max_batch = int(max_batch)
        self.slots = [None] * self.max_batch
        self.waiting = deque()
        self.queued_tokens = 0
        self._tok_lock = threading.Lock()
        self._admit_counter = 0

    # ------------------------------------------------------- queue
    def add(self, req):
        self.waiting.append(req)
        with self._tok_lock:
            self.queued_tokens += len(req.prompt) + len(req.generated)

    def push_front(self, req):
        """Re-queue at the head (preemption / failed admission).
        Bypasses admission control by design: a preempted request
        was already admitted once — shedding it now would turn
        memory pressure into a client-visible failure."""
        self.waiting.appendleft(req)
        with self._tok_lock:
            self.queued_tokens += len(req.prompt) + len(req.generated)

    def pop_waiting(self):
        if not self.waiting:
            return None
        req = self.waiting.popleft()
        with self._tok_lock:
            self.queued_tokens -= len(req.prompt) + len(req.generated)
        return req

    def remove_waiting(self, req):
        """Remove one specific queued request in place (the reap
        sweep's deadline/cancel path).  Removal — not pop-all-and-
        re-push — so the queue never transits an empty state a
        concurrent ``submit()`` admission check or a SIGTERM-time
        ``snapshot()`` could observe.  Returns False when absent."""
        try:
            self.waiting.remove(req)
        except ValueError:
            return False
        with self._tok_lock:
            self.queued_tokens -= len(req.prompt) + len(req.generated)
        return True

    def has_waiting(self):
        return bool(self.waiting)

    # ------------------------------------------------------- slots
    def free_slot(self):
        """Index of a free slot, or None when the batch is full."""
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def place(self, req, slot):
        assert self.slots[slot] is None
        self.slots[slot] = req
        req.slot = slot
        if req.last_slot is None:
            req.last_slot = slot    # lane pin: first admission wins
        req.state = RUNNING
        req.admit_seq = self._admit_counter
        self._admit_counter += 1

    def clear(self, req):
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None

    def running(self):
        return [r for r in self.slots if r is not None]

    def n_running(self):
        return sum(1 for r in self.slots if r is not None)

    def any_running(self):
        return any(r is not None for r in self.slots)

    def latest_running(self):
        """Preemption victim: the most recently admitted runner."""
        live = self.running()
        return max(live, key=lambda r: r.admit_seq) if live else None

    def has_work(self):
        return bool(self.waiting) or self.any_running()
