"""Production inference serving tier (docs/serving.md).

Continuous batching + paged KV cache + prefix caching + int8 weight
quantization over ``TransformerLM`` — the traffic-serving layer the
reference's C predict ABI never needed to be.

    from incubator_mxnet_tpu import serving
    eng = serving.ServingEngine(model, max_batch=8)
    req = eng.submit(prompt_tokens, max_new_tokens=64)
    for r, tok in eng.stream():
        ...

Or over an exported artifact: ``predictor.serve(param_file, model)``.

Multi-replica fleet (docs/serving.md "Fleet"): ``ServingRouter``
spreads requests over N ``ReplicaServer`` processes speaking the
``rpc`` frame protocol, with prefix-affinity routing, circuit
breakers, and failover re-dispatch.
"""
from .block_table import BlockPool, BlockPoolExhausted
from .cache_manager import PrefixCache
from .engine import ServingEngine
from .quantize import (quantization_error, quantize_weights,
                       weights_nbytes)
from .replica import ReplicaServer
from .router import FleetRequest, ServingRouter
from .rpc import (RpcClient, RpcError, RpcFrameError, RpcServer,
                  RpcTimeoutError)
from .scheduler import (CANCELLED, EXPIRED, FAILED, FINISHED, QUEUED,
                        RUNNING, TERMINAL_STATES, Request,
                        RequestTooLargeError, Scheduler,
                        SchedulingError, ServeRejectedError,
                        ServingError)

__all__ = ["ServingEngine", "BlockPool", "BlockPoolExhausted",
           "PrefixCache", "Request", "Scheduler", "ServingError",
           "SchedulingError", "ServeRejectedError",
           "RequestTooLargeError", "quantize_weights",
           "quantization_error", "weights_nbytes", "QUEUED",
           "RUNNING", "FINISHED", "FAILED", "EXPIRED", "CANCELLED",
           "TERMINAL_STATES", "ServingRouter", "FleetRequest",
           "ReplicaServer", "RpcClient", "RpcServer", "RpcError",
           "RpcTimeoutError", "RpcFrameError"]
