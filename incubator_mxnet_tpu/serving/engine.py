"""Production inference serving engine: continuous batching over a
paged KV cache (docs/serving.md).

``TransformerLM.generate`` decodes one fixed-shape batch per call —
fine for a notebook, fatal at traffic: a mixed stream pays worst-case
padding, head-of-line blocking, and a dense max-length KV buffer per
sequence.  :class:`ServingEngine` replaces that with:

- **Paged KV cache** — per-layer block pools
  (``block_table.BlockPool``); each request owns just the blocks its
  actual length needs, gather/scatter happens by block id INSIDE the
  jitted step, and refcounting makes shared system prompts copy-free
  (``cache_manager.PrefixCache``).
- **Continuous batching** — ``submit()`` enqueues, every ``step()``
  admits waiting requests into free batch slots (one suffix prefill
  each) and runs ONE decode step for the whole batch; finished
  requests retire and free their blocks the same iteration.  Because
  liveness is data (scratch-block rows), not shape, the decode step
  compiles ONCE per engine and admission/retirement never retrace.
- **int8 weight quantization** (``quantize.quantize_weights``) for
  weight-stream density, dequantized inside the jit.

The decode loop's only device->host sync is the per-iteration token
read (enforced by ci/lint.py's host-sync rule over this module).
Telemetry rides the process registry: request/ token counters,
queue-wait / TTFT / per-token histograms, occupancy and
pool-utilization gauges.  ``MXTPU_FAULT_SPEC`` scope
``serve:request`` poisons the nth admission: the request is evicted
(state ``failed``) without touching its batchmates.
"""
import itertools
import threading
import time
import weakref
from collections import deque

import numpy as np

from .. import resilience, telemetry, tracing
from ..utils.env import get_env
from ..utils.log import get_logger
from .block_table import BlockPool, BlockPoolExhausted
from .cache_manager import PrefixCache
from .quantize import quantize_weights
from .scheduler import (FAILED, FINISHED, QUEUED, Request, Scheduler,
                        SchedulingError)

__all__ = ["ServingEngine"]

# process-unique engine ids: request ids restart at 0 per engine, so
# trace events carry (engine, rid) — a post-mortem dump spanning two
# engines must never conflate their requests
_ENGINE_IDS = itertools.count()


def _next_pow2(n):
    return 1 << max(0, int(n - 1)).bit_length()


class ServingEngine:
    """Continuous-batching decode engine over one TransformerLM.

    Parameters (env defaults in parentheses; docs/env_vars.md):

    model : an initialized TransformerLM (``attn_window`` must be 0)
    max_batch : concurrent decode slots (``MXTPU_SERVE_MAX_BATCH``)
    block_size : tokens per KV block (``MXTPU_SERVE_BLOCK_SIZE``)
    num_blocks : pool size incl. the reserved scratch block
        (``MXTPU_SERVE_NUM_BLOCKS``)
    quantize : ``"off"`` or ``"int8"`` (``MXTPU_SERVE_QUANT``)
    prefix_cache : share prompt-prefix KV blocks across requests
        (``MXTPU_SERVE_PREFIX_CACHE``)
    keep_logits : retain each slot's last-step logits on the request
        (device array; for validation/debugging — never host-read by
        the engine)

    Decoding is greedy (temperature-0) — the batch-invariant mode
    whose outputs are provably identical to sequential
    ``generate()``; sampling policies layer on later without
    touching the cache machinery.

    The engine is single-threaded: ``submit()`` may be called from
    anywhere, but ``step()``/``stream()``/``run()`` must be driven
    from one thread.
    """

    def __init__(self, model, max_batch=None, block_size=None,
                 num_blocks=None, quantize=None, prefix_cache=None,
                 keep_logits=False):
        from ..gluon.model_zoo.transformer import TransformerLM
        if not isinstance(model, TransformerLM):
            raise TypeError(
                "ServingEngine serves TransformerLM models, got "
                f"{type(model).__name__}")
        model._check_paged()
        self.block_size = int(block_size if block_size is not None
                              else get_env("MXTPU_SERVE_BLOCK_SIZE"))
        self.num_blocks = int(num_blocks if num_blocks is not None
                              else get_env("MXTPU_SERVE_NUM_BLOCKS"))
        self.max_batch = int(max_batch if max_batch is not None
                             else get_env("MXTPU_SERVE_MAX_BATCH"))
        if self.block_size < 1 or self.max_batch < 1:
            raise ValueError(
                f"bad serving config: block_size={self.block_size}, "
                f"max_batch={self.max_batch}")
        quantize = (get_env("MXTPU_SERVE_QUANT")
                    if quantize is None else quantize)
        if prefix_cache is None:
            prefix_cache = get_env("MXTPU_SERVE_PREFIX_CACHE")

        self.model = model
        # one table row spans the model's full context budget
        self.max_blocks = -(-model._max_len // self.block_size)
        self.pool = BlockPool(self.num_blocks, self.block_size)
        self.cache = PrefixCache(self.pool, enabled=prefix_cache)
        self._sched = Scheduler(self.max_batch)
        self.keep_logits = bool(keep_logits)

        wts = self._settled_weights(model)
        if quantize in ("int8", True):
            self._wts = quantize_weights(wts)
            self.quantized = True
        elif quantize in ("off", "", False, None):
            self._wts = wts
            self.quantized = False
        else:
            raise ValueError(
                f"quantize must be 'off' or 'int8', got {quantize!r}")

        import jax.numpy as jnp
        kvh = model.n_kv_heads
        dh = model._d // model.n_heads
        shape = (self.num_blocks, self.block_size, kvh, dh)
        self._kpools = [jnp.zeros(shape, jnp.float32)
                        for _ in range(model.n_layers)]
        self._vpools = [jnp.zeros(shape, jnp.float32)
                        for _ in range(model.n_layers)]

        self._step_fn = None
        self._prefill_fns = {}
        self.trace_counts = {}
        self._next_id = 0
        self._submit_lock = threading.Lock()
        self._completed = []        # retired/failed since last run()
        # flight recorder: compile attribution for the traced
        # builders, terminal per-request summaries for stats(), and
        # KV-pool bytes attributed in the device-memory gauges (via
        # a weakref so the process-wide provider table never pins a
        # dropped engine)
        self.engine_id = next(_ENGINE_IDS)
        # per-engine ledger site: jit caches are per-engine, so two
        # identically-configured engines genuinely compile twice —
        # a shared site would attribute the second as 'duplicate'
        self._ledger = tracing.compile_ledger(
            f"serving_engine:{self.engine_id}")
        self._req_summaries = deque(maxlen=1024)
        # serving lanes are static: name them once instead of
        # re-storing the same mapping per async event on the decode
        # path (set_lane_name takes the profiler lock)
        from .. import profiler
        profiler._profiler.set_lane_name(
            profiler.SERVE_QUEUE_LANE, "serve queue")
        for s in range(self.max_batch):
            profiler._profiler.set_lane_name(
                profiler.SERVE_SLOT_LANE0 + s, f"serve slot {s}")
        ref = weakref.ref(self)

        def _kv_arrays():
            eng = ref()
            if eng is None:
                return []
            return list(eng._kpools) + list(eng._vpools)

        self._mem_unregister = tracing.register_memory(
            "kv_pools", _kv_arrays, owner=self)
        tracing.install_signal_dump()

        # telemetry handles cached once (no-ops when disabled)
        self._m_requests = telemetry.counter("serving_requests_total")
        self._m_tokens = telemetry.counter("serving_tokens_total")
        self._m_prefill = telemetry.counter(
            "serving_prefill_tokens_total")
        self._m_hits = telemetry.counter(
            "serving_prefix_cache_hits_total")
        self._m_misses = telemetry.counter(
            "serving_prefix_cache_misses_total")
        self._m_preempt = telemetry.counter(
            "serving_preemptions_total")
        self._m_evict = telemetry.counter("serving_evictions_total")
        self._m_occ = telemetry.gauge("serving_batch_occupancy")
        self._m_util = telemetry.gauge(
            "serving_block_pool_utilization")
        self._h_wait = telemetry.histogram(
            "serving_queue_wait_seconds")
        self._h_ttft = telemetry.histogram("serving_ttft_seconds")
        self._h_tok = telemetry.histogram(
            "serving_token_latency_seconds")

    # ---------------------------------------------------------- setup
    @staticmethod
    def _settled_weights(model):
        from ..gluon.parameter import DeferredInitializationError
        try:
            return model._decode_weights()
        except DeferredInitializationError:
            # deferred-init params (LayerNorm shapes): settle with a
            # tiny probe forward, exactly as generate() does
            import jax.numpy as jnp

            from .. import autograd, ndarray as nd
            with autograd.pause():
                model.forward(
                    nd.NDArray(jnp.zeros((1, 1), jnp.int32)))
            return model._decode_weights()

    def _counted_jit(self, name, fn, signature):
        import jax

        def traced(*args):
            # runs at TRACE time only: the regression tests assert
            # admission/retirement replay the compiled step
            self.trace_counts[name] = \
                self.trace_counts.get(name, 0) + 1
            return fn(*args)

        # donate the KV pools (args 1, 2 in both the prefill and the
        # step signature): the compiled call updates the cache IN
        # PLACE instead of copying every pool array out per token —
        # the engine always rebinds self._kpools/_vpools from the
        # outputs, so the consumed buffers are never reused
        jfn = jax.jit(traced, donate_argnums=(1, 2))

        def called(*args):
            # a call that ran the Python trace just compiled: record
            # the retrace with its wall time + signature attribution
            # (an unexpected re-trace of the decode step is exactly
            # the storm MXTPU_COMPILE_BUDGET watches for)
            before = self.trace_counts.get(name, 0)
            t0 = time.monotonic()
            out = jfn(*args)
            if self.trace_counts.get(name, 0) > before:
                self._ledger.record(signature, time.monotonic() - t0)
            return out

        return called

    def _get_step_fn(self):
        if self._step_fn is None:
            self._step_fn = self._counted_jit(
                "decode", self.model._build_paged_step(
                    self.max_batch, self.max_blocks,
                    self.block_size),
                {"builder": "decode",
                 "static_arg": (self.max_batch, self.max_blocks,
                                self.block_size)})
        return self._step_fn

    def _get_prefill_fn(self, suffix_len):
        # pow2 buckets, floored at one block: a prefix-cache hit can
        # shrink the suffix to a couple of tokens, and compiling a
        # dedicated tiny executable per length would cost far more
        # than the padded rows it saves
        bucket = min(max(_next_pow2(suffix_len),
                         _next_pow2(self.block_size)),
                     _next_pow2(self.model._max_len))
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._prefill_fns[bucket] = self._counted_jit(
                f"prefill_{bucket}", self.model._build_paged_prefill(
                    bucket, self.max_blocks, self.block_size),
                {"builder": "prefill", "shape": (bucket,),
                 "static_arg": (self.max_blocks, self.block_size)})
        return bucket, fn

    # ------------------------------------------------------------- API
    def submit(self, tokens, max_new_tokens, eos_id=None):
        """Enqueue a prompt; returns its :class:`Request` handle.

        ``tokens`` is a 1D int sequence (list / numpy / NDArray).
        The handle's ``generated`` list fills as the engine runs
        (drive it via :meth:`step`, :meth:`stream` or :meth:`run`)."""
        if hasattr(tokens, "asnumpy"):
            tokens = tokens.asnumpy()
        toks = [int(t) for t in np.asarray(tokens).ravel()]
        max_new = int(max_new_tokens)
        if not toks:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1 (got {max_new})")
        total = len(toks) + max_new
        if total > self.model._max_len:
            raise ValueError(
                f"prompt+new = {total} exceeds max_len "
                f"{self.model._max_len}")
        need = -(-total // self.block_size)
        if need > min(self.max_blocks, self.pool.capacity):
            raise ValueError(
                f"request needs {need} blocks but the pool serves "
                f"at most {min(self.max_blocks, self.pool.capacity)}"
                " per sequence — raise MXTPU_SERVE_NUM_BLOCKS or "
                "shrink the request")
        with self._submit_lock:     # submit() may race across threads
            req = Request(self._next_id, toks, max_new,
                          eos_id=eos_id)
            self._next_id += 1
            # lifecycle + async events fire BEFORE the scheduler can
            # see the request: once added, a concurrent engine
            # thread may admit it immediately, and serve_admit must
            # never carry a lower seq than serve_enqueue
            tracing.trace_event("serve_enqueue", rid=req.id,
                                engine=self.engine_id,
                                prompt_tokens=len(toks),
                                max_new_tokens=max_new)
            self._prof_async("b", "request", req)
            self._prof_async("b", "queue_wait", req)
            self._sched.add(req)
        self._m_requests.inc()
        return req

    def has_work(self):
        """Whether any submitted request is still queued/running."""
        return self._sched.has_work()

    def step(self):
        """One continuous-batching iteration: admit -> grow ->
        decode -> retire.  Returns the ``(request, token_id)``
        events emitted this iteration."""
        events = []
        self._admit(events)
        if self._sched.any_running():
            self._grow()
        if self._sched.any_running():
            self._decode_once(events)
        self._m_occ.set(self._sched.n_running() / self.max_batch)
        self._m_util.set(self.pool.utilization())
        return events

    def stream(self):
        """Drive the engine, yielding ``(request, token_id)`` events
        as they are produced, until all submitted work drains."""
        while self._sched.has_work():
            for ev in self.step():
                yield ev

    def run(self):
        """Drain everything; returns ``{request_id: full token
        list}`` for every request that finished during this call
        (failed requests are included with their partial output —
        check ``request.state``)."""
        for _ev in self.stream():
            pass
        done, self._completed = self._completed, []
        return {req.id: req.tokens for req in done}

    # ------------------------------------------------------ internals
    def _alloc(self, n):
        """Pool alloc with prefix-cache eviction as the fallback."""
        try:
            return self.pool.alloc(n)
        except BlockPoolExhausted:
            self.cache.evict(n - self.pool.num_free)
            return self.pool.alloc(n)       # may re-raise

    def _admit(self, events):
        """Fill free slots from the waiting queue; one suffix
        prefill per admission (prefix-cache hits skip the shared
        blocks)."""
        import jax
        import jax.numpy as jnp
        while self._sched.has_waiting():
            slot = self._sched.free_slot()
            if slot is None:
                return
            req = self._sched.pop_waiting()
            try:
                resilience.inject("serve", "request")
            except resilience.TransientError as exc:
                self._fail(req, exc)
                continue
            toks = req.tokens
            matched, n_cached = self.cache.match(toks)
            need = -(-len(toks) // self.block_size) - len(matched)
            try:
                fresh = self._alloc(need)
            except BlockPoolExhausted:
                if matched:
                    self.pool.free(matched)     # release the match
                self._sched.push_front(req)
                if not self._sched.any_running():
                    raise SchedulingError(
                        f"request {req.id} needs {need} fresh "
                        "blocks but the pool cannot ever provide "
                        "them — raise MXTPU_SERVE_NUM_BLOCKS")
                return                          # wait for frees
            req.admit_ts = time.monotonic()
            # per-segment wait: a preempted request's requeue
            # restarted the clock, so re-admission must not count
            # its earlier prefill/decode time as queue wait
            wait = req.admit_ts - req.enqueue_ts
            req.queue_wait_s += wait
            self._h_wait.observe(wait)
            self._m_hits.inc(n_cached)
            self._m_misses.inc(len(toks) - n_cached)
            req.block_ids = matched + fresh
            self._sched.place(req, slot)
            tracing.trace_event(
                "serve_admit", rid=req.id, engine=self.engine_id,
                slot=slot,
                blocks=len(req.block_ids), cached_tokens=n_cached,
                queue_wait_s=round(wait, 6),
                preemptions=req.preemptions)
            self._prof_async("e", "queue_wait", req)
            self._prof_async("b", "prefill", req)

            suffix = toks[n_cached:]
            bucket, fn = self._get_prefill_fn(len(suffix))
            suf = np.zeros(bucket, np.int32)
            suf[:len(suffix)] = suffix
            row = np.zeros(self.max_blocks, np.int32)
            row[:len(req.block_ids)] = req.block_ids
            t_pre = time.monotonic()
            with telemetry.span("serve_prefill"):
                self._kpools, self._vpools, nxt, logits = fn(
                    self._wts, self._kpools, self._vpools,
                    jnp.asarray(row), np.int32(n_cached),
                    jnp.asarray(suf), np.int32(len(suffix)))
                # completion barrier, not a transfer: dispatching the
                # next call while its DONATED pool buffers are still
                # pending hits a pathological slow path (~7x) in the
                # runtime's donation bookkeeping
                jax.block_until_ready(self._kpools)
            dt_pre = time.monotonic() - t_pre
            req.prefill_s += dt_pre
            tracing.trace_event(
                "serve_prefill", rid=req.id, engine=self.engine_id,
                slot=slot,
                suffix_tokens=len(suffix), bucket=bucket,
                seconds=round(dt_pre, 6))
            self._prof_async("e", "prefill", req)
            self._prof_async("b", "decode", req)
            self._m_prefill.inc(len(suffix))
            if self.keep_logits:
                req.logits = logits
            # register this stream's full blocks for future sharing
            self.cache.insert(toks, req.block_ids)
            req.n_past = len(toks)
            tok = int(np.asarray(nxt))  # sync-ok: first-token read seeds the decode loop
            self._append_token(req, tok, events)

    def _grow(self):
        """Ensure every runner owns the block its next position
        writes into; preempt the latest-admitted runner on
        exhaustion."""
        bs = self.block_size
        for req in sorted(self._sched.running(),
                          key=lambda r: r.admit_seq):
            if req.done or req.slot is None:
                continue        # preempted earlier in this pass
            if req.n_past // bs < len(req.block_ids):
                continue
            while True:
                try:
                    req.block_ids += self._alloc(1)
                    break
                except BlockPoolExhausted:
                    victim = self._sched.latest_running()
                    if victim is req and self._sched.n_running() == 1:
                        raise SchedulingError(
                            "block pool exhausted with a single "
                            "running request — the pool cannot hold "
                            "one full sequence; raise "
                            "MXTPU_SERVE_NUM_BLOCKS")
                    self._preempt(victim)
                    if victim is req:
                        break               # we preempted ourselves

    def _preempt(self, req):
        """Free a runner's blocks and re-queue it (front).  Its
        generated tokens survive; re-admission re-prefills
        prompt+generated (cheap again once the prefix cache holds
        the shared blocks)."""
        freed = len(req.block_ids)
        self._sched.clear(req)
        if req.block_ids:
            self.pool.free(req.block_ids)
        req.block_ids = []
        req.n_past = 0
        req.state = QUEUED
        req.preemptions += 1
        self._m_preempt.inc()
        # a preempted runner is queued again: its queue-wait clock
        # restarts here (decomposition stays truthful across cycles)
        req.enqueue_ts = time.monotonic()
        tracing.trace_event(
            "serve_preempt", rid=req.id, engine=self.engine_id,
            generated_tokens=len(req.generated), freed_blocks=freed,
            preemptions=req.preemptions)
        self._prof_async("e", "decode", req)
        self._sched.push_front(req)
        tracing.trace_event("serve_requeue", rid=req.id,
                            engine=self.engine_id,
                            queue_depth=len(self._sched.waiting))
        self._prof_async("b", "queue_wait", req)

    def _decode_once(self, events):
        """One batched decode step + the per-iteration token read."""
        import jax
        import jax.numpy as jnp
        B, MB = self.max_batch, self.max_blocks
        tokens = np.zeros(B, np.int32)
        npast = np.zeros(B, np.int32)
        tables = np.zeros((B, MB), np.int32)
        slots = self._sched.slots
        for i, req in enumerate(slots):
            if req is None:
                continue
            tokens[i] = req.generated[-1]
            npast[i] = req.n_past
            tables[i, :len(req.block_ids)] = req.block_ids
        fn = self._get_step_fn()
        with telemetry.span("serve_decode"):
            self._kpools, self._vpools, nxt, logits = fn(
                self._wts, self._kpools, self._vpools,
                jnp.asarray(tables), jnp.asarray(npast),
                jnp.asarray(tokens))
            # completion barrier (see _admit): the token read below
            # already serializes the loop; waiting on the donated
            # pools too keeps the NEXT dispatch off the slow path
            jax.block_until_ready(self._kpools)
        toks = np.asarray(nxt)  # sync-ok: the per-iteration token read
        for i, req in enumerate(list(slots)):
            if req is None:
                continue
            req.n_past += 1
            if self.keep_logits:
                req.logits = logits[i]
            self._append_token(req, int(toks[i]), events)

    def _append_token(self, req, tok, events):
        """Record one emitted token; retire the request when its
        budget or EOS is reached."""
        now = time.monotonic()
        if req.first_token_ts is None:
            req.first_token_ts = now
            self._h_ttft.observe(now - req.submit_ts)
            tracing.trace_event(
                "serve_first_token", rid=req.id,
                engine=self.engine_id,
                ttft_s=round(now - req.submit_ts, 6),
                queue_wait_s=round(req.queue_wait_s, 6),
                prefill_s=round(req.prefill_s, 6))
        else:
            self._h_tok.observe(now - req.last_token_ts)
        req.last_token_ts = now
        req.generated.append(tok)
        self._m_tokens.inc()
        events.append((req, tok))
        if (len(req.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)):
            self._retire(req)

    def _retire(self, req):
        self._sched.clear(req)
        if req.block_ids:
            self.pool.free(req.block_ids)
        req.block_ids = []
        req.state = FINISHED
        req.finish_ts = time.monotonic()
        self._completed.append(req)
        tracing.trace_event(
            "serve_retire", rid=req.id, engine=self.engine_id,
            tokens_generated=len(req.generated),
            preemptions=req.preemptions,
            queue_wait_s=round(req.queue_wait_s, 6),
            prefill_s=round(req.prefill_s, 6))
        self._terminal_async(req, "decode")
        self._req_summaries.append(self._request_summary(req))

    def _fail(self, req, exc):
        """Evict a poisoned request without touching batchmates.

        Observability parity with retirement: the queue wait is
        recorded (an admission-time eviction would otherwise leave
        the wait histogram blind to the request), a terminal
        ``serve_evict`` event closes the lifecycle, and the flight
        recorder dumps (MXTPU_TRACE_DUMP) — an eviction is a fault,
        and the ring holds the request's whole story."""
        get_logger().warning(
            "serving: evicting request %s after injected/terminal "
            "fault: %s", req.id, exc)
        now = time.monotonic()
        # _fail only fires on requests popped from the queue (fresh
        # or requeued-after-preemption), so a queue-wait segment is
        # always open here — close it, like admission does
        wait = now - req.enqueue_ts
        req.queue_wait_s += wait
        self._h_wait.observe(wait)
        self._sched.clear(req)
        if req.block_ids:
            self.pool.free(req.block_ids)
        req.block_ids = []
        req.state = FAILED
        req.error = exc
        req.finish_ts = now
        self._m_evict.inc()
        self._completed.append(req)
        tracing.trace_event(
            "serve_evict", rid=req.id, engine=self.engine_id,
            error=str(exc),
            tokens_generated=len(req.generated),
            queue_wait_s=round(req.queue_wait_s, 6),
            preemptions=req.preemptions)
        self._terminal_async(req, "queue_wait")
        self._req_summaries.append(self._request_summary(req))
        tracing.dump_on_fault("serving_eviction")

    # -------------------------------------------------- observability
    def _prof_async(self, ph, name, req):
        """Emit one chrome-tracing async (b/e) event for a request
        phase when the profiler is running; each request id is an
        async track, placed on a named serving lane.  Lane choice is
        a function of the PHASE, not of ``req.slot`` at emission
        time — slot is nulled by ``Scheduler.clear`` before terminal
        events fire, and every phase of one request must land on one
        lane: ``request``/``queue_wait`` live on the queue lane,
        compute phases (``prefill``/``decode``) on the slot of the
        request's FIRST admission (``last_slot``, pinned in
        ``Scheduler.place`` and never cleared — re-admission into a
        different slot must not split the track)."""
        from .. import profiler
        prof = profiler._profiler
        if not prof.running:
            return
        if name in ("request", "queue_wait") or req.last_slot is None:
            lane = profiler.SERVE_QUEUE_LANE
        else:
            lane = profiler.SERVE_SLOT_LANE0 + req.last_slot
        prof.add_async_event(name,
                             f"req{self.engine_id}.{req.id}", ph,
                             category="serving", lane=lane)

    def _terminal_async(self, req, open_phase):
        """Close a request's open async phases at its terminal
        transition.  ``open_phase`` is the phase still open at that
        point: always ``decode`` for retirement (opened at the last
        admission), always ``queue_wait`` for eviction — ``_fail``
        only fires on requests popped from the queue, including
        preempted ones whose requeue re-opened the wait."""
        self._prof_async("e", open_phase, req)
        self._prof_async("e", "request", req)

    @staticmethod
    def _request_summary(req):
        """One request's TTFT decomposition for :meth:`stats`."""
        ttft = (req.first_token_ts - req.submit_ts
                if req.first_token_ts is not None else None)
        decode = (req.last_token_ts - req.first_token_ts
                  if req.first_token_ts is not None
                  and req.last_token_ts is not None else None)
        return {
            "id": req.id, "state": req.state,
            "prompt_tokens": len(req.prompt),
            "tokens_generated": len(req.generated),
            "preemptions": req.preemptions,
            "queue_wait_s": round(req.queue_wait_s, 6),
            "prefill_s": round(req.prefill_s, 6),
            "ttft_s": round(ttft, 6) if ttft is not None else None,
            "decode_s": (round(decode, 6)
                         if decode is not None else None),
            "error": (str(req.error)
                      if req.error is not None else None),
        }

    def stats(self):
        """Engine observability snapshot: per-request lifecycle
        summaries (terminal requests from the bounded summary ring,
        live ones in flight), trace/compile counts, and pool state.
        Host-side bookkeeping only — no device access; safe to call
        from a monitoring thread while the engine runs
        (tracing.safe_list absorbs concurrent deque mutation)."""
        live = [self._request_summary(r)
                for r in tracing.safe_list(self._sched.waiting)
                + self._sched.running()]
        return {
            "requests": tracing.safe_list(self._req_summaries),
            "live": live,
            "trace_counts": dict(self.trace_counts),
            "batch_occupancy":
                self._sched.n_running() / self.max_batch,
            "pool_utilization": self.pool.utilization(),
        }
