"""Production inference serving engine: continuous batching over a
paged KV cache (docs/serving.md).

``TransformerLM.generate`` decodes one fixed-shape batch per call —
fine for a notebook, fatal at traffic: a mixed stream pays worst-case
padding, head-of-line blocking, and a dense max-length KV buffer per
sequence.  :class:`ServingEngine` replaces that with:

- **Paged KV cache** — per-layer block pools
  (``block_table.BlockPool``); each request owns just the blocks its
  actual length needs, gather/scatter happens by block id INSIDE the
  jitted step, and refcounting makes shared system prompts copy-free
  (``cache_manager.PrefixCache``).
- **Continuous batching** — ``submit()`` enqueues, every ``step()``
  admits waiting requests into free batch slots (one suffix prefill
  each) and runs ONE decode step for the whole batch; finished
  requests retire and free their blocks the same iteration.  Because
  liveness is data (scratch-block rows), not shape, the decode step
  compiles ONCE per engine and admission/retirement never retrace.
- **int8 weight quantization** (``quantize.quantize_weights``) for
  weight-stream density, dequantized inside the jit.

The SLO/survival layer (docs/serving.md "SLOs, shedding, and
drain") rides the same loop: per-request TTFT/total **deadlines**
enforced on monotonic clocks (terminal ``expired``), client
**cancellation** (``cancel()`` / abandoned ``stream_request()``,
terminal ``cancelled``), **admission control** (bounded queue +
queued-token budget -> typed ``ServeRejectedError`` at ``submit()``),
graceful **drain** + atomic **snapshot/restore** of all in-flight
requests (greedy recompute makes the continuation token-identical,
SIGTERM wired to snapshot-then-drain), and a **decode-step
watchdog** dumping the flight recorder on budget overruns.  Every
new path is injectable: ``MXTPU_FAULT_SPEC`` scopes ``serve:step`` /
``serve:deadline`` / ``serve:queue`` next to ``serve:request``.

The decode loop's only device->host sync is the per-iteration token
read (enforced by ci/lint.py's host-sync rule over this module).
Telemetry rides the process registry: request/ token counters,
queue-wait / TTFT / per-token histograms, occupancy and
pool-utilization gauges.  ``MXTPU_FAULT_SPEC`` scope
``serve:request`` poisons the nth admission: the request is evicted
(state ``failed``) without touching its batchmates.
"""
import itertools
import os
import threading
import time
import weakref
from collections import deque

import numpy as np

from .. import resilience, telemetry, tracing
from ..utils.env import get_env
from ..utils.log import get_logger
from .block_table import BlockPool, BlockPoolExhausted
from .cache_manager import PrefixCache
from .quantize import quantize_weights
from .scheduler import (CANCELLED, EXPIRED, FAILED, FINISHED, QUEUED,
                        Request, RequestTooLargeError, Scheduler,
                        SchedulingError, ServeRejectedError)

__all__ = ["ServingEngine"]

SNAPSHOT_VERSION = 1

# process-unique engine ids: request ids restart at 0 per engine, so
# trace events carry (engine, rid) — a post-mortem dump spanning two
# engines must never conflate their requests
_ENGINE_IDS = itertools.count()


def _next_pow2(n):
    return 1 << max(0, int(n - 1)).bit_length()


class ServingEngine:
    """Continuous-batching decode engine over one TransformerLM.

    Parameters (env defaults in parentheses; docs/env_vars.md):

    model : an initialized TransformerLM (``attn_window`` must be 0)
    max_batch : concurrent decode slots (``MXTPU_SERVE_MAX_BATCH``)
    block_size : tokens per KV block (``MXTPU_SERVE_BLOCK_SIZE``)
    num_blocks : pool size incl. the reserved scratch block
        (``MXTPU_SERVE_NUM_BLOCKS``); pass ``"auto"`` to size the
        pool from memory-planner headroom — capacity minus weights
        and decode workspace (docs/memory.md), refusing with a typed
        error when the model alone cannot fit
    quantize : ``"off"`` or ``"int8"`` (``MXTPU_SERVE_QUANT``)
    prefix_cache : share prompt-prefix KV blocks across requests
        (``MXTPU_SERVE_PREFIX_CACHE``)
    keep_logits : retain each slot's last-step logits on the request
        (device array; for validation/debugging — never host-read by
        the engine)
    ttft_deadline / deadline : default per-request SLOs in seconds
        (``MXTPU_SERVE_TTFT_DEADLINE`` / ``MXTPU_SERVE_DEADLINE``;
        0 disables) — ``submit(..., ttft_deadline=, deadline=)``
        overrides per request
    queue_limit / queue_tokens : admission control
        (``MXTPU_SERVE_QUEUE_LIMIT`` / ``MXTPU_SERVE_QUEUE_TOKENS``;
        0 = unbounded): past either bound ``submit()`` sheds with a
        typed :class:`ServeRejectedError`
    step_timeout : decode-step watchdog budget in seconds
        (``MXTPU_SERVE_STEP_TIMEOUT``; 0 disables)

    Decoding is greedy (temperature-0) — the batch-invariant mode
    whose outputs are provably identical to sequential
    ``generate()``; sampling policies layer on later without
    touching the cache machinery.

    The engine is single-threaded: ``submit()`` may be called from
    anywhere, but ``step()``/``stream()``/``run()`` must be driven
    from one thread.
    """

    def __init__(self, model, max_batch=None, block_size=None,
                 num_blocks=None, quantize=None, prefix_cache=None,
                 keep_logits=False, ttft_deadline=None,
                 deadline=None, queue_limit=None, queue_tokens=None,
                 step_timeout=None):
        from ..gluon.model_zoo.transformer import TransformerLM
        if not isinstance(model, TransformerLM):
            raise TypeError(
                "ServingEngine serves TransformerLM models, got "
                f"{type(model).__name__}")
        model._check_paged()
        self.block_size = int(block_size if block_size is not None
                              else get_env("MXTPU_SERVE_BLOCK_SIZE"))
        raw_blocks = num_blocks if num_blocks is not None \
            else get_env("MXTPU_SERVE_NUM_BLOCKS")
        # num_blocks="auto": size the pool from planner headroom
        # (docs/memory.md) once the weights are settled below
        self.auto_blocks = (isinstance(raw_blocks, str)
                            and raw_blocks.lower() == "auto")
        self.num_blocks = 0 if self.auto_blocks else int(raw_blocks)
        self.max_batch = int(max_batch if max_batch is not None
                             else get_env("MXTPU_SERVE_MAX_BATCH"))
        if self.block_size < 1 or self.max_batch < 1:
            raise ValueError(
                f"bad serving config: block_size={self.block_size}, "
                f"max_batch={self.max_batch}")
        quantize = (get_env("MXTPU_SERVE_QUANT")
                    if quantize is None else quantize)
        if prefix_cache is None:
            prefix_cache = get_env("MXTPU_SERVE_PREFIX_CACHE")
        # SLO/survival knobs (docs/serving.md "SLOs, shedding, and
        # drain"); every deadline comparison is monotonic-clock
        # (lint-enforced — wall clock jumps must never expire work)
        self.ttft_deadline = float(
            ttft_deadline if ttft_deadline is not None
            else get_env("MXTPU_SERVE_TTFT_DEADLINE"))
        self.deadline = float(
            deadline if deadline is not None
            else get_env("MXTPU_SERVE_DEADLINE"))
        self.queue_limit = int(
            queue_limit if queue_limit is not None
            else get_env("MXTPU_SERVE_QUEUE_LIMIT"))
        self.queue_tokens = int(
            queue_tokens if queue_tokens is not None
            else get_env("MXTPU_SERVE_QUEUE_TOKENS"))
        self.step_timeout = float(
            step_timeout if step_timeout is not None
            else get_env("MXTPU_SERVE_STEP_TIMEOUT"))

        self.model = model
        # one table row spans the model's full context budget
        self.max_blocks = -(-model._max_len // self.block_size)
        self._sched = Scheduler(self.max_batch)
        self.keep_logits = bool(keep_logits)

        # weights settle BEFORE the pool: auto pool sizing needs the
        # real (possibly quantized) weight bytes on the chip
        wts = self._settled_weights(model)
        if quantize in ("int8", True):
            self._wts = quantize_weights(wts)
            self.quantized = True
        elif quantize in ("off", "", False, None):
            self._wts = wts
            self.quantized = False
        else:
            raise ValueError(
                f"quantize must be 'off' or 'int8', got {quantize!r}")

        import jax.numpy as jnp
        kvh = model.n_kv_heads
        dh = model._d // model.n_heads
        if self.auto_blocks:
            self.num_blocks = self._auto_num_blocks(kvh, dh)
        if self.num_blocks < 1:
            raise ValueError(
                f"bad serving config: num_blocks={self.num_blocks}")
        self.pool = BlockPool(self.num_blocks, self.block_size)
        self.cache = PrefixCache(self.pool, enabled=prefix_cache)
        shape = (self.num_blocks, self.block_size, kvh, dh)
        self._kpools = [jnp.zeros(shape, jnp.float32)
                        for _ in range(model.n_layers)]
        self._vpools = [jnp.zeros(shape, jnp.float32)
                        for _ in range(model.n_layers)]

        self._step_fn = None
        self._prefill_fns = {}
        self.trace_counts = {}
        self._next_id = 0
        self._submit_lock = threading.Lock()
        self._completed = []        # terminal since last run()/drain()
        # SLO/survival state: live requests by id (cancel() target),
        # terminal-state counts for stats(), the drain latch, and
        # two cheap arm counters that keep the reap sweep off the
        # decode hot path when no deadline/cancel is pending
        self._live = {}
        self._terminal_counts = {}
        self._draining = False
        self._deadlines_armed = 0
        self._cancels_pending = 0
        # earliest armed deadline stamp: the reap sweep skips the
        # queue walk entirely until the clock reaches it (or a
        # cancel is pending); recomputed by every sweep
        self._deadline_next = float("inf")
        # the one request mid-transit between queue and slot
        # (_admit pop->place, _preempt clear->requeue): a SIGTERM
        # snapshot() interrupting that window must still see it —
        # it is in neither sched.waiting nor sched.slots
        self._in_transit = None
        # lock-free dirty bit for stream_request abandons: set with
        # a plain store from whatever thread GC runs the finalizer
        # on (cancel()'s lock would deadlock there); tells the reap
        # sweep to run even though _cancels_pending was not bumped
        self._abandon_flagged = False
        # flight recorder: compile attribution for the traced
        # builders, terminal per-request summaries for stats(), and
        # KV-pool bytes attributed in the device-memory gauges (via
        # a weakref so the process-wide provider table never pins a
        # dropped engine)
        self.engine_id = next(_ENGINE_IDS)
        # per-engine ledger site: jit caches are per-engine, so two
        # identically-configured engines genuinely compile twice —
        # a shared site would attribute the second as 'duplicate'
        self._ledger = tracing.compile_ledger(
            f"serving_engine:{self.engine_id}")
        self._req_summaries = deque(maxlen=1024)
        # serving lanes are static: name them once instead of
        # re-storing the same mapping per async event on the decode
        # path (set_lane_name takes the profiler lock)
        from .. import profiler
        profiler._profiler.set_lane_name(
            profiler.SERVE_QUEUE_LANE, "serve queue")
        for s in range(self.max_batch):
            profiler._profiler.set_lane_name(
                profiler.SERVE_SLOT_LANE0 + s, f"serve slot {s}")
        ref = weakref.ref(self)

        def _kv_arrays():
            eng = ref()
            if eng is None:
                return []
            return list(eng._kpools) + list(eng._vpools)

        self._mem_unregister = tracing.register_memory(
            "kv_pools", _kv_arrays, owner=self)
        tracing.install_signal_dump()

        # telemetry handles cached once (no-ops when disabled)
        self._m_requests = telemetry.counter("serving_requests_total")
        self._m_tokens = telemetry.counter("serving_tokens_total")
        self._m_prefill = telemetry.counter(
            "serving_prefill_tokens_total")
        self._m_hits = telemetry.counter(
            "serving_prefix_cache_hits_total")
        self._m_misses = telemetry.counter(
            "serving_prefix_cache_misses_total")
        self._m_preempt = telemetry.counter(
            "serving_preemptions_total")
        self._m_evict = telemetry.counter("serving_evictions_total")
        self._m_occ = telemetry.gauge("serving_batch_occupancy")
        self._m_util = telemetry.gauge(
            "serving_block_pool_utilization")
        self._h_wait = telemetry.histogram(
            "serving_queue_wait_seconds")
        self._h_ttft = telemetry.histogram("serving_ttft_seconds")
        self._h_tok = telemetry.histogram(
            "serving_token_latency_seconds")
        self._m_rejected = telemetry.counter(
            "serving_rejected_total")
        self._m_expired = telemetry.counter("serving_expired_total")
        self._m_cancelled = telemetry.counter(
            "serving_cancelled_total")
        self._m_drains = telemetry.counter("serving_drains_total")
        self._m_qdepth = telemetry.gauge("serving_queue_depth")
        self._m_qtokens = telemetry.gauge(
            "serving_queued_prompt_tokens")
        # perf observatory (docs/observability.md): MFU from the
        # analytic decode-FLOPs ledger — token counts and context
        # lengths are already host-side, so this adds no syncs
        self._m_mfu = telemetry.gauge("serving_mfu")
        self._m_ftok = telemetry.gauge("serving_flops_per_token")
        self._perf_interval = max(1, int(get_env(
            "MXTPU_PERF_INTERVAL")))
        self._perf_flops = 0.0
        self._perf_tokens = 0
        self._perf_iters = 0
        self._perf_t0 = None
        self._perf_caps = None

    # ---------------------------------------------------------- setup
    def _auto_num_blocks(self, kvh, dh):
        """Size the KV pool from planner headroom (docs/memory.md):
        usable device capacity (MXTPU_HBM_BYTES override honored,
        MXTPU_MEM_GATE_MARGIN reserved) minus the settled weights and
        a per-step decode workspace (hidden states + logits), divided
        by per-block KV bytes — capped at a full context row for
        every slot plus the scratch block, so tiny models never hoard
        the chip.  Refuses with a typed MemoryPlanError when the
        model alone leaves no room for one block per slot."""
        from ..perf import memory_planner as mp
        from ..perf.device_db import headroom, hbm_capacity
        wts_bytes = mp.tree_bytes(self._wts)
        d = self.model._d
        vocab = self.model.head._units
        # decode workspace: one step's logits + residual stream per
        # slot (fp32), the transient XLA scratch next to the pools
        workspace = 4.0 * self.max_batch * (vocab + 8 * d)
        per_block = 2.0 * self.model.n_layers * self.block_size \
            * kvh * dh * 4
        avail = headroom(wts_bytes + workspace)
        floor = self.max_batch + 1   # one block per slot + scratch
        if avail < per_block * floor:
            from ..resilience import MemoryPlanError
            plan = mp.MemoryPlan(
                params=wts_bytes, activations=workspace,
                kv_pool=per_block * floor,
                meta={"site": "serving_engine",
                      "num_blocks": floor,
                      "quantized": self.quantized})
            raise MemoryPlanError("serving_engine", plan,
                                  capacity=hbm_capacity())
        n = int(avail // per_block)
        cap = self.max_batch * self.max_blocks + 1
        n = min(n, cap)
        plan = mp.MemoryPlan(
            params=wts_bytes, activations=workspace,
            kv_pool=per_block * n,
            meta={"site": "serving_engine", "num_blocks": n,
                  "quantized": self.quantized})
        mp._publish_plan(plan)
        import logging
        logging.getLogger("mxtpu.memory").info(
            "serving KV pool auto-sized: %d blocks (%s)", n,
            plan.describe())
        return n

    @staticmethod
    def _settled_weights(model):
        from ..gluon.parameter import DeferredInitializationError
        try:
            return model._decode_weights()
        except DeferredInitializationError:
            # deferred-init params (LayerNorm shapes): settle with a
            # tiny probe forward, exactly as generate() does
            import jax.numpy as jnp

            from .. import autograd, ndarray as nd
            with autograd.pause():
                model.forward(
                    nd.NDArray(jnp.zeros((1, 1), jnp.int32)))
            return model._decode_weights()

    def _counted_jit(self, name, fn, signature):
        import jax

        def traced(*args):
            # runs at TRACE time only: the regression tests assert
            # admission/retirement replay the compiled step
            self.trace_counts[name] = \
                self.trace_counts.get(name, 0) + 1
            return fn(*args)

        # donate the KV pools (args 1, 2 in both the prefill and the
        # step signature): the compiled call updates the cache IN
        # PLACE instead of copying every pool array out per token —
        # the engine always rebinds self._kpools/_vpools from the
        # outputs, so the consumed buffers are never reused
        jfn = jax.jit(traced, donate_argnums=(1, 2))

        def called(*args):
            # a call that ran the Python trace just compiled: record
            # the retrace with its wall time + signature attribution
            # (an unexpected re-trace of the decode step is exactly
            # the storm MXTPU_COMPILE_BUDGET watches for)
            before = self.trace_counts.get(name, 0)
            t0 = time.monotonic()
            out = jfn(*args)
            if self.trace_counts.get(name, 0) > before:
                self._ledger.record(signature, time.monotonic() - t0)
            return out

        return called

    def _get_step_fn(self):
        if self._step_fn is None:
            self._step_fn = self._counted_jit(
                "decode", self.model._build_paged_step(
                    self.max_batch, self.max_blocks,
                    self.block_size),
                {"builder": "decode",
                 "static_arg": (self.max_batch, self.max_blocks,
                                self.block_size)})
        return self._step_fn

    def _get_prefill_fn(self, suffix_len):
        # pow2 buckets, floored at one block: a prefix-cache hit can
        # shrink the suffix to a couple of tokens, and compiling a
        # dedicated tiny executable per length would cost far more
        # than the padded rows it saves
        bucket = min(max(_next_pow2(suffix_len),
                         _next_pow2(self.block_size)),
                     _next_pow2(self.model._max_len))
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._prefill_fns[bucket] = self._counted_jit(
                f"prefill_{bucket}", self.model._build_paged_prefill(
                    bucket, self.max_blocks, self.block_size),
                {"builder": "prefill", "shape": (bucket,),
                 "static_arg": (self.max_blocks, self.block_size)})
        return bucket, fn

    # ------------------------------------------------------------- API
    def _check_servable(self, n_tokens, max_new):
        """Raise :class:`RequestTooLargeError` when a request of
        ``n_tokens`` prompt + ``max_new`` generated tokens can NEVER
        be served by this engine — queueing it would hang the
        schedule forever (docs/serving.md)."""
        total = n_tokens + max_new
        if total > self.model._max_len:
            raise RequestTooLargeError(
                f"prompt+new = {total} exceeds max_len "
                f"{self.model._max_len}")
        need = -(-total // self.block_size)
        if need > min(self.max_blocks, self.pool.capacity):
            raise RequestTooLargeError(
                f"request needs {need} blocks but the pool serves "
                f"at most {min(self.max_blocks, self.pool.capacity)}"
                " per sequence — raise MXTPU_SERVE_NUM_BLOCKS or "
                "shrink the request")

    def _reject(self, n_tokens, reason):
        """Shed one submission: exactly one terminal trace event
        (queue context attached — a rejected request never waited,
        the event says what it would have waited behind), counters,
        then the typed raise."""
        depth = len(self._sched.waiting)
        qtok = self._sched.queued_tokens
        self._m_rejected.inc()
        self._terminal_counts["rejected"] = \
            self._terminal_counts.get("rejected", 0) + 1
        tracing.trace_event(
            "serve_reject", engine=self.engine_id,
            prompt_tokens=n_tokens, reason=reason,
            queue_depth=depth, queued_tokens=qtok)
        raise ServeRejectedError(
            f"request rejected ({reason}): queue depth {depth}"
            f"/{self.queue_limit or 'inf'}, queued tokens {qtok}"
            f"/{self.queue_tokens or 'inf'} — shedding keeps "
            "admitted requests' latency bounded (docs/serving.md)")

    def submit(self, tokens, max_new_tokens, eos_id=None,
               ttft_deadline=None, deadline=None):
        """Enqueue a prompt; returns its :class:`Request` handle.

        ``tokens`` is a 1D int sequence (list / numpy / NDArray).
        The handle's ``generated`` list fills as the engine runs
        (drive it via :meth:`step`, :meth:`stream` or :meth:`run`).

        ``ttft_deadline`` / ``deadline`` (seconds; default the
        engine's env-configured SLOs, 0/None = none) bound first
        token and total completion — a request past either expires
        (state ``expired``, blocks freed) instead of occupying the
        engine.  Raises :class:`RequestTooLargeError` when the
        request can never fit the pool/context, and
        :class:`ServeRejectedError` when admission control sheds it
        (bounded queue, token budget, or draining engine)."""
        if hasattr(tokens, "asnumpy"):
            tokens = tokens.asnumpy()
        toks = [int(t) for t in np.asarray(tokens).ravel()]
        max_new = int(max_new_tokens)
        if not toks:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1 (got {max_new})")
        self._check_servable(len(toks), max_new)
        if ttft_deadline is None:
            ttft_deadline = self.ttft_deadline
        if deadline is None:
            deadline = self.deadline
        with self._submit_lock:     # submit() may race across threads
            # admission control: shed at the door — a bounded queue
            # turns overload into fast typed failures instead of
            # unbounded TTFT collapse.  Preemption requeues bypass
            # this (push_front): they were already admitted.
            if self._draining:
                self._reject(len(toks), "draining")
            if self.queue_limit > 0 and \
                    len(self._sched.waiting) >= self.queue_limit:
                self._reject(len(toks), "queue_limit")
            if self.queue_tokens > 0 and \
                    self._sched.queued_tokens + len(toks) \
                    > self.queue_tokens:
                self._reject(len(toks), "queue_tokens")
            try:
                # injectable shedding: MXTPU_FAULT_SPEC
                # serve:queue:N:error rejects the Nth submission
                resilience.inject("serve", "queue")
            except resilience.TransientError:
                self._reject(len(toks), "injected")
            req = Request(self._next_id, toks, max_new,
                          eos_id=eos_id)
            self._next_id += 1
            now = time.monotonic()
            try:
                # injectable SLO breach: serve:deadline:N:error
                # forces the Nth submission to expire at the next
                # engine iteration, whatever its configured deadline
                resilience.inject("serve", "deadline")
            except resilience.TransientError:
                req.deadline_ts = now - 1.0
            else:
                if ttft_deadline and ttft_deadline > 0:
                    req.ttft_deadline_ts = now + float(ttft_deadline)
                if deadline and deadline > 0:
                    req.deadline_ts = now + float(deadline)
            if req.ttft_deadline_ts is not None \
                    or req.deadline_ts is not None:
                self._deadlines_armed += 1
                self._deadline_next = min(self._deadline_next,
                                          self._next_deadline(req))
            # lifecycle + async events fire BEFORE the scheduler can
            # see the request: once added, a concurrent engine
            # thread may admit it immediately, and serve_admit must
            # never carry a lower seq than serve_enqueue
            tracing.trace_event("serve_enqueue", rid=req.id,
                                engine=self.engine_id,
                                prompt_tokens=len(toks),
                                max_new_tokens=max_new)
            self._prof_async("b", "request", req)
            self._prof_async("b", "queue_wait", req)
            self._live[req.id] = req
            self._sched.add(req)
            self._m_qdepth.set(len(self._sched.waiting))
            self._m_qtokens.set(self._sched.queued_tokens)
        self._m_requests.inc()
        return req

    def cancel(self, rid):
        """Request cancellation of a live request by id (thread-safe;
        clients may call it from any thread, including a stream
        consumer that lost interest).  Honored at the next engine
        iteration: the request reaches terminal state ``cancelled``
        with its partial output retained and every pool block freed
        — cancellation can never leak blocks.  Returns True when the
        request was live and is now marked; False when unknown or
        already terminal."""
        with self._submit_lock:
            req = self._live.get(rid)
            if req is None or req.done or req.cancel_requested:
                return False
            req.cancel_requested = True
            req.cancel_counted = True
            self._cancels_pending += 1
            return True

    def has_work(self):
        """Whether driving the engine can still make progress: any
        request queued/running — or, while draining, only the
        RUNNING batch.  Queued requests are frozen for
        :meth:`snapshot` once drain latches; reporting them here
        would spin a ``while engine.has_work(): engine.step()``
        driver forever on work admission will never start."""
        return self._has_loop_work()

    def _has_loop_work(self):
        """What drives stream()/run(): everything, or — while
        draining — only the running batch (queued requests are
        deliberately left for snapshot(), never admitted)."""
        if self._draining:
            return self._sched.any_running()
        return self._sched.has_work()

    def step(self):
        """One continuous-batching iteration: reap (cancellations +
        expired deadlines, blocks freed same-iteration) -> admit ->
        grow -> decode -> retire.  Returns the ``(request,
        token_id)`` events emitted this iteration."""
        events = []
        self._reap()
        self._admit(events)
        if self._sched.any_running():
            self._grow()
        if self._sched.any_running():
            self._decode_once(events)
        self._m_occ.set(self._sched.n_running() / self.max_batch)
        self._m_util.set(self.pool.utilization())
        self._m_qdepth.set(len(self._sched.waiting))
        self._m_qtokens.set(self._sched.queued_tokens)
        self._perf_iters += 1
        if self._perf_iters >= self._perf_interval:
            self._publish_perf()
        return events

    # ------------------------------------------------ perf observatory
    def _serve_dtype(self):
        """Weight-stream dtype for roofline math: int8 when the
        weights are quantized, else the device's native matmul
        width (bf16 on TPU, fp32 elsewhere)."""
        if self.quantized:
            return "int8"
        import jax
        return ("bfloat16" if jax.devices()[0].platform == "tpu"
                else "float32")

    def _caps(self):
        if self._perf_caps is None:
            import jax
            from ..perf import caps_for
            self._perf_caps = caps_for(jax.devices()[0])
        return self._perf_caps

    def _publish_perf(self):
        """Publish ``serving_mfu`` / ``serving_flops_per_token`` from
        the decode-FLOPs ledger accumulated over the last
        MXTPU_PERF_INTERVAL iterations.  Wall-clock only."""
        now = time.monotonic()
        if self._perf_t0 is not None and self._perf_tokens:
            dt = now - self._perf_t0
            if dt > 0:
                peak = self._caps().peak(self._serve_dtype())
                if peak:
                    self._m_mfu.set(self._perf_flops / dt / peak)
                self._m_ftok.set(
                    self._perf_flops / self._perf_tokens)
        self._perf_t0 = now
        self._perf_flops = 0.0
        self._perf_tokens = 0
        self._perf_iters = 0

    def perf_report(self, context_len=None, batch=None):
        """Analytic per-family cost/roofline report for one batched
        decode step (docs/observability.md "Perf observatory").

        Defaults reflect the live batch: ``context_len`` is the mean
        running KV length (half the model's context when idle) and
        ``batch`` is the running-slot count (``max_batch`` when
        idle).  Pure host arithmetic — safe to call in production."""
        from ..perf import transformer_decode_cost
        m = self.model
        running = [r for r in self._sched.slots if r is not None]
        if context_len is None:
            context_len = (
                int(sum(r.n_past for r in running) / len(running))
                if running else max(1, m._max_len // 2))
        if batch is None:
            batch = len(running) or self.max_batch
        dtype = self._serve_dtype()
        dtype_size = {"int8": 1, "bfloat16": 2}.get(dtype, 4)
        rep = transformer_decode_cost(
            d_model=m._d, n_layers=m.n_layers,
            vocab=m.head._units, context_len=context_len,
            n_heads=m.n_heads, n_kv_heads=m.n_kv_heads,
            mlp_ratio=m._mlp_ratio, attn_window=m.attn_window,
            moe_experts=m.moe_experts, batch=batch,
            dtype_size=dtype_size)
        from ..perf import roofline
        caps = self._caps()
        return {
            "context_len": int(context_len),
            "batch": int(batch),
            "dtype": dtype,
            "device": caps.kind,
            "flops_per_token": float(
                m.decode_flops_per_token(context_len)),
            "per_family": rep.table(caps, dtype),
            "total": rep.summary(),
            "roofline": roofline(rep.flops, rep.bytes, caps, dtype),
        }

    def stream(self):
        """Drive the engine, yielding ``(request, token_id)`` events
        as they are produced, until all submitted work drains (or,
        while draining, until the running batch finishes)."""
        while self._has_loop_work():
            for ev in self.step():
                yield ev

    def stream_request(self, req):
        """Drive the engine yielding ``req``'s tokens only — the
        per-client streaming view.  ABANDONING the generator (break
        / ``close()`` / GC — started or not) cancels the request: a
        client that hung up must not keep burning decode slots and
        KV blocks.  The abandon path only FLAGS the cancellation,
        with plain attribute stores — a GC finalizer may run it on
        any thread, even reentrantly inside ``step()`` or under the
        submit lock, where taking a lock or mutating scheduler/pool
        state would deadlock or corrupt the iteration — and the
        next engine iteration finalizes it as CANCELLED, freeing
        its blocks.  A NORMAL exit (the request finished, or drain
        latched and the loop ran out of work) cancels nothing: a
        drained-but-queued request belongs to :meth:`snapshot`."""
        # shared cell, not a local: a generator abandoned before its
        # first next() never enters the body (GEN_CREATED close/GC
        # runs no code), so the body's finally cannot cover that
        # case — the weakref.finalize on the generator object does,
        # and the cell tells it a normal exhaustion already happened
        state = {"exhausted": False}

        def _flag():
            if not state["exhausted"] and not req.done:
                req.cancel_requested = True
                self._abandon_flagged = True

        gen = self._stream_gen(req, state, _flag)
        weakref.finalize(gen, _flag)
        return gen

    def _stream_gen(self, req, state, flag):
        # yield from a CURSOR over req.generated, not from this
        # generator's own step() events: continuous batching means
        # other drivers (run()/stream()/a sibling stream_request)
        # may decode this request's tokens — append-only list, so
        # the cursor never misses one, whoever produced it
        sent = 0
        try:
            while True:
                while sent < len(req.generated):
                    yield req.generated[sent]
                    sent += 1
                if req.done or not self._has_loop_work():
                    break
                self.step()
            state["exhausted"] = True
        finally:
            flag()

    def run(self):
        """Drain everything; returns ``{request_id: full token
        list}`` for every request that reached a terminal state
        during this call (failed / expired / cancelled ones included
        with their partial output — check ``request.state``)."""
        for _ev in self.stream():
            pass
        done, self._completed = self._completed, []
        return {req.id: req.tokens for req in done}

    def drain(self, run=True):
        """Graceful shutdown, phase one: stop admission (subsequent
        ``submit()`` calls shed with ``ServeRejectedError``), keep
        queued requests queued — they belong to :meth:`snapshot` —
        and, with ``run=True``, finish the currently RUNNING batch.
        Returns the terminal requests collected since the last
        ``run()``/``drain()`` as ``{id: tokens}``.  Idempotent."""
        self._latch_drain()
        if run:
            while self._sched.any_running():
                self.step()
        done, self._completed = self._completed, []
        return {req.id: req.tokens for req in done}

    def _latch_drain(self):
        """Latch admission off (idempotent): counter + the one
        ``serve_drain`` event fire on the first latch, however it
        happens — ``drain()`` or the SIGTERM handler.  Touches no
        ``_completed`` state, so it is safe from a signal handler
        interrupting ``run()``."""
        if self._draining:
            return
        self._draining = True
        self._m_drains.inc()
        tracing.trace_event(
            "serve_drain", engine=self.engine_id,
            running=self._sched.n_running(),
            queue_depth=len(self._sched.waiting))

    # -------------------------------------------- snapshot / restore
    def _snapshot_request(self, req, now):
        """One in-flight request's resumable state.  Deadlines are
        persisted as REMAINING seconds (monotonic stamps are
        meaningless in another process); a negative remainder means
        the restored request expires on its first iteration, which
        is exactly the SLO truth."""
        # observability parity across the crash: a QUEUED request's
        # wait segment is still open — close it into the persisted
        # total exactly like every terminal path does, or the
        # restored lifecycle under-reports its pre-crash wait
        wait = req.queue_wait_s
        if req.state == QUEUED:
            wait += now - req.enqueue_ts
        return {
            "id": req.id,
            "prompt": list(req.prompt),
            "generated": list(req.generated),
            "max_new_tokens": req.max_new_tokens,
            "eos_id": req.eos_id,
            "queue_wait_s": wait,
            "prefill_s": req.prefill_s,
            "preemptions": req.preemptions,
            "ttft_done": req.first_token_ts is not None,
            "ttft_remaining_s": (
                req.ttft_deadline_ts - now
                if req.ttft_deadline_ts is not None else None),
            "deadline_remaining_s": (
                req.deadline_ts - now
                if req.deadline_ts is not None else None),
        }

    def snapshot(self, path=None):
        """Persist every in-flight request (running by admission
        order first, then the waiting queue in order) so a fresh
        engine can :meth:`restore` them.  A request is fully
        reconstructible from prompt + generated tokens: greedy
        recompute (the same property preemption relies on) makes the
        restored continuation token-identical.

        Returns the snapshot dict; with ``path`` it is also written
        via ``resilience.atomic_save`` (+ CRC32 sidecar), so a
        SIGTERM-time snapshot a reader observes is whole or absent,
        never torn.  Safe to call from a signal handler interrupting
        the engine thread: only host-side Python state is read, each
        request's ``generated`` list is append-only, and a request
        the signal caught mid-transit between queue and slot
        (``_in_transit``) is captured too — it is in neither
        ``waiting`` nor ``slots`` during that window."""
        now = time.monotonic()
        running = sorted(
            (r for r in list(self._sched.slots) if r is not None),
            key=lambda r: r.admit_seq)
        transit = self._in_transit
        # index-walk, not iteration/safe_list: client threads only
        # APPEND to the waiting deque (removal is engine-loop-only,
        # and a signal handler freezes that very thread), so walking
        # by index yields a consistent snapshot where an iterator
        # would raise on a concurrent append — and a degrade-to-
        # empty fallback would silently drop the whole queue from
        # the crash-resume file
        waiting = []
        i = 0
        while True:
            try:
                waiting.append(self._sched.waiting[i])
            except IndexError:
                break
            i += 1
        # cancel-flagged requests are excluded (the client already
        # hung up — a restore must not resurrect them); the id
        # dedup covers a transit pointer that already landed back
        # in a slot or the queue
        reqs, seen = [], set()
        # the _live straggler sweep is the safety net: if the engine
        # loop runs on a DIFFERENT thread than this snapshot (not
        # the documented signal-handler-freezes-the-loop case), a
        # concurrently-popped request can be missing from all three
        # views above for an instant — _live holds every non-
        # terminal request regardless, so none can vanish from the
        # crash-resume file (it merely lands at the queue's tail)
        for r in (list(running)
                  + ([transit] if transit is not None else [])
                  + waiting
                  + list(self._live.copy().values())):
            if r.id in seen or r.done or r.cancel_requested:
                continue
            seen.add(r.id)
            reqs.append(self._snapshot_request(r, now))
        snap = {
            "version": SNAPSHOT_VERSION,
            "engine": {"max_batch": self.max_batch,
                       "block_size": self.block_size,
                       "num_blocks": self.num_blocks,
                       "prefix_cache": self.cache.enabled,
                       "quantize": ("int8" if self.quantized
                                    else "off"),
                       "max_len": self.model._max_len},
            "next_id": self._next_id,
            "requests": reqs,
        }
        tracing.trace_event("serve_snapshot", engine=self.engine_id,
                            requests=len(reqs),
                            path=str(path) if path else None)
        if path is not None:
            import pickle
            resilience.atomic_save(
                path, lambda f: pickle.dump(snap, f))
        return snap

    @classmethod
    def restore(cls, model, snapshot, **engine_kw):
        """Build a fresh engine and re-queue every request of a
        :meth:`snapshot` (a path, or the dict itself).  Restored
        requests continue by greedy recompute — re-admission
        prefills ``prompt + generated``, exactly the preemption
        path — so completed outputs are token-identical to an
        uninterrupted run.  Engine geometry defaults to the
        snapshot's; explicit ``engine_kw`` overrides win, and a
        request the new geometry can never serve fails loudly at
        admission (typed, per-request) instead of hanging the
        schedule."""
        if isinstance(snapshot, (str, os.PathLike)):
            import pickle
            path = os.fspath(snapshot)
            snapshot = resilience.decode_or_corrupt(
                path, lambda: pickle.loads(
                    resilience.read_validated_bytes(path)))
        if not isinstance(snapshot, dict) or \
                snapshot.get("version") != SNAPSHOT_VERSION or \
                "requests" not in snapshot:
            raise resilience.CheckpointCorruptError(
                "not a serving snapshot (or an incompatible "
                f"version): {snapshot!r:.80}")
        cfg = snapshot.get("engine", {})
        for key in ("max_batch", "block_size", "num_blocks",
                    "prefix_cache", "quantize"):
            if cfg.get(key) is not None:
                engine_kw.setdefault(key, cfg[key])
        eng = cls(model, **engine_kw)
        for entry in snapshot["requests"]:
            eng.resubmit(entry)
        with eng._submit_lock:
            eng._next_id = max(
                eng._next_id, int(snapshot.get("next_id", 0)))
        tracing.trace_event("serve_restore", engine=eng.engine_id,
                            requests=len(snapshot["requests"]))
        return eng

    def resubmit(self, entry, redispatch=False):
        """Re-admit ONE request in :meth:`_snapshot_request` entry
        form — the shared re-admission path under :meth:`restore`
        (crash resume) and the fleet router's failover re-dispatch
        (serving/router.py ships exactly this schema over rpc.py to
        a surviving replica).  Continues by greedy recompute:
        re-admission prefills ``prompt + generated``, so the
        completed output is token-identical to an uninterrupted run.

        Bypasses admission control deliberately — the request was
        already admitted once (at the original engine or fleet-wide
        at the router); shedding it here would turn one failure into
        two.  Deadlines in the entry are REMAINING seconds and are
        re-armed against this process's monotonic clock; a request
        whose first token already shipped (``ttft_done``) does not
        re-arm TTFT and never re-emits ``serve_first_token``
        (lifecycle parity: one first token per request, ever).
        Returns the :class:`Request`."""
        now = time.monotonic()
        complete = False   # retired OUTSIDE the lock: _finalize takes it
        with self._submit_lock:
            req = Request(int(entry["id"]), entry["prompt"],
                          entry["max_new_tokens"],
                          eos_id=entry.get("eos_id"))
            req.generated = [int(t)
                             for t in entry.get("generated", [])]
            req.queue_wait_s = float(
                entry.get("queue_wait_s", 0.0))
            req.prefill_s = float(entry.get("prefill_s", 0.0))
            req.preemptions = int(entry.get("preemptions", 0))
            rem = entry.get("deadline_remaining_s")
            if rem is not None:
                req.deadline_ts = now + float(rem)
            rem = entry.get("ttft_remaining_s")
            # a request whose first token shipped pre-crash met
            # its TTFT SLO; the re-prefill must not re-arm it —
            # and must not re-emit serve_first_token or observe
            # a second TTFT sample (lifecycle parity: one first
            # token per request, ever)
            if entry.get("ttft_done"):
                req.first_token_ts = now
                req.last_token_ts = now
            elif rem is not None:
                req.ttft_deadline_ts = now + float(rem)
            tracing.trace_event(
                "serve_enqueue", rid=req.id,
                engine=self.engine_id,
                prompt_tokens=len(req.prompt),
                max_new_tokens=req.max_new_tokens,
                restored=True, redispatch=bool(redispatch),
                generated_tokens=len(req.generated))
            self._prof_async("b", "request", req)
            self._prof_async("b", "queue_wait", req)
            self._live[req.id] = req
            self._m_requests.inc()
            self._next_id = max(self._next_id, req.id + 1)
            # a snapshot can catch a request BETWEEN its last
            # generated token and its same-iteration retirement
            # (req.done latches at _retire): that request is
            # already complete — re-queueing it would decode
            # one token past its budget/EOS and break the
            # token-identical resume guarantee
            if (len(req.generated) >= req.max_new_tokens
                    or (req.eos_id is not None and req.generated
                        and req.generated[-1] == req.eos_id)):
                complete = True
            else:
                if req.ttft_deadline_ts is not None \
                        or req.deadline_ts is not None:
                    self._deadlines_armed += 1
                    self._deadline_next = min(
                        self._deadline_next,
                        self._next_deadline(req))
                self._sched.add(req)
        if complete:
            self._retire(req)   # exactly-one-terminal parity holds
        return req

    def take_completed(self):
        """Pop and return the terminal :class:`Request` objects
        collected since the last ``run()``/``drain()``/
        ``take_completed()`` — WITHOUT latching drain.  The fleet
        replica's serve loop (serving/replica.py) consumes terminals
        incrementally this way while staying open for new
        dispatches; ``run()`` and ``drain()`` keep their
        consume-on-return semantics."""
        with self._submit_lock:
            done, self._completed = self._completed, []
        return done

    def install_sigterm(self, snapshot_path, drain=True):
        """Wire SIGTERM to snapshot-then-drain: the handler writes
        an atomic :meth:`snapshot` of every in-flight request to
        ``snapshot_path``, then latches :meth:`drain` mode so the
        loop finishes the running batch and ``run()``/``stream()``
        return (the process exits normally — the signal is consumed).
        With ``drain=False`` the previous SIGTERM disposition runs
        instead right after the snapshot (default disposition:
        process dies — the crash-resume flavor; a fresh process
        :meth:`restore`\\ s the snapshot).

        Main-thread only (signal.signal's rule); returns False
        when it cannot install.  Chains whatever PYTHON handler was
        there — tracing.install_signal_dump's post-mortem, another
        engine's snapshot hook — on every path; with ``drain=True``
        only the default-disposition re-raise is suppressed (it
        would kill the process drain means to let exit — though a
        chained handler that itself escalates still terminates,
        with snapshot and dump on disk).  Falls back to the
        previous disposition entirely once the engine is garbage-
        collected (the handler only holds a weakref; it must never
        consume SIGTERM on behalf of an engine that no longer
        exists)."""
        import signal as _signal
        if threading.current_thread() is not threading.main_thread():
            return False
        prev = _signal.getsignal(_signal.SIGTERM)
        eng_ref = weakref.ref(self)

        def handler(num, frame):
            eng = eng_ref()
            if eng is not None:
                try:
                    eng.snapshot(snapshot_path)
                except Exception:   # a torn dump must not mask the
                    pass            # signal's actual handling
                try:
                    eng._latch_drain()  # drains_total counts SIGTERM
                except Exception:       # the latch must hold even if
                    eng._draining = True    # telemetry raises
                if drain:
                    # consume the signal for THIS engine's graceful
                    # exit, but still run any chained Python handler
                    # first — another engine's snapshot hook or
                    # tracing's post-mortem dump must not be
                    # silenced by whoever installed last.  Only the
                    # default-disposition re-raise is suppressed
                    # (that would kill the process drain means to
                    # let exit); a chained handler that itself
                    # escalates leaves the snapshot + dump behind —
                    # the crash-resume flavor with artifacts.
                    if callable(prev):
                        prev(num, frame)
                    return
                # drain=False: fall through to the previous
                # disposition right after the snapshot
            # engine already gone (or drain=False): the previous
            # disposition must run — a dead weakref consuming every
            # SIGTERM would make the process unkillable by anything
            # short of SIGKILL
            if callable(prev):
                prev(num, frame)
            elif prev == _signal.SIG_IGN:
                return
            else:
                _signal.signal(num, _signal.SIG_DFL)
                _signal.raise_signal(num)

        try:
            _signal.signal(_signal.SIGTERM, handler)
        except (ValueError, OSError):
            return False
        return True

    # ------------------------------------------------------ internals
    def _alloc(self, n):
        """Pool alloc with prefix-cache eviction as the fallback."""
        try:
            return self.pool.alloc(n)
        except BlockPoolExhausted:
            self.cache.evict(n - self.pool.num_free)
            return self.pool.alloc(n)       # may re-raise

    def _admit(self, events):
        """Fill free slots from the waiting queue; one suffix
        prefill per admission (prefix-cache hits skip the shared
        blocks)."""
        import jax
        import jax.numpy as jnp
        if self._draining:
            return      # drain(): queued requests belong to snapshot()
        while self._sched.has_waiting():
            slot = self._sched.free_slot()
            if slot is None:
                return
            # publish to the snapshot pointer BEFORE popping: a
            # signal landing between the two statements sees the
            # request in both places (id-dedup) — after a bare pop
            # it would be in neither.  Visible until placed,
            # requeued, or terminal (terminals filter on req.done).
            self._in_transit = self._sched.waiting[0]
            req = self._sched.pop_waiting()
            try:
                resilience.inject("serve", "request")
            except resilience.TransientError as exc:
                self._fail(req, exc)
                continue
            try:
                # re-check at admission: a snapshot restored into a
                # smaller pool/context must fail THAT request loudly,
                # not hang the schedule (submit() already vets fresh
                # submissions; preemption cannot grow the bound)
                self._check_servable(len(req.prompt),
                                     req.max_new_tokens)
            except RequestTooLargeError as exc:
                self._fail(req, exc)
                continue
            toks = req.tokens
            matched, n_cached = self.cache.match(toks)
            need = -(-len(toks) // self.block_size) - len(matched)
            try:
                fresh = self._alloc(need)
            except BlockPoolExhausted:
                if matched:
                    self.pool.free(matched)     # release the match
                self._sched.push_front(req)
                self._in_transit = None
                if not self._sched.any_running():
                    raise SchedulingError(
                        f"request {req.id} needs {need} fresh "
                        "blocks but the pool cannot ever provide "
                        "them — raise MXTPU_SERVE_NUM_BLOCKS")
                return                          # wait for frees
            req.admit_ts = time.monotonic()
            # per-segment wait: a preempted request's requeue
            # restarted the clock, so re-admission must not count
            # its earlier prefill/decode time as queue wait
            wait = req.admit_ts - req.enqueue_ts
            req.queue_wait_s += wait
            self._h_wait.observe(wait)
            self._m_hits.inc(n_cached)
            self._m_misses.inc(len(toks) - n_cached)
            req.block_ids = matched + fresh
            self._sched.place(req, slot)
            self._in_transit = None
            tracing.trace_event(
                "serve_admit", rid=req.id, engine=self.engine_id,
                slot=slot,
                blocks=len(req.block_ids), cached_tokens=n_cached,
                queue_wait_s=round(wait, 6),
                preemptions=req.preemptions)
            self._prof_async("e", "queue_wait", req)
            self._prof_async("b", "prefill", req)

            suffix = toks[n_cached:]
            bucket, fn = self._get_prefill_fn(len(suffix))
            suf = np.zeros(bucket, np.int32)
            suf[:len(suffix)] = suffix
            row = np.zeros(self.max_blocks, np.int32)
            row[:len(req.block_ids)] = req.block_ids
            t_pre = time.monotonic()
            with telemetry.span("serve_prefill"):
                self._kpools, self._vpools, nxt, logits = fn(
                    self._wts, self._kpools, self._vpools,
                    jnp.asarray(row), np.int32(n_cached),
                    jnp.asarray(suf), np.int32(len(suffix)))
                # completion barrier, not a transfer: dispatching the
                # next call while its DONATED pool buffers are still
                # pending hits a pathological slow path (~7x) in the
                # runtime's donation bookkeeping
                jax.block_until_ready(self._kpools)
            dt_pre = time.monotonic() - t_pre
            req.prefill_s += dt_pre
            tracing.trace_event(
                "serve_prefill", rid=req.id, engine=self.engine_id,
                slot=slot,
                suffix_tokens=len(suffix), bucket=bucket,
                seconds=round(dt_pre, 6))
            self._prof_async("e", "prefill", req)
            self._prof_async("b", "decode", req)
            self._m_prefill.inc(len(suffix))
            if self.keep_logits:
                req.logits = logits
            # register this stream's full blocks for future sharing
            self.cache.insert(toks, req.block_ids)
            req.n_past = len(toks)
            tok = int(np.asarray(nxt))  # sync-ok: first-token read seeds the decode loop
            self._append_token(req, tok, events)

    def _grow(self):
        """Ensure every runner owns the block its next position
        writes into; preempt the latest-admitted runner on
        exhaustion."""
        bs = self.block_size
        for req in sorted(self._sched.running(),
                          key=lambda r: r.admit_seq):
            if req.done or req.slot is None:
                continue        # preempted earlier in this pass
            if req.n_past // bs < len(req.block_ids):
                continue
            while True:
                try:
                    req.block_ids += self._alloc(1)
                    break
                except BlockPoolExhausted:
                    victim = self._sched.latest_running()
                    if victim is req and self._sched.n_running() == 1:
                        # the pool cannot hold this one sequence:
                        # fail THE REQUEST loudly (typed, terminal,
                        # blocks freed) instead of raising out of
                        # step() or — worse — spinning forever
                        self._fail(req, SchedulingError(
                            "block pool exhausted with a single "
                            "running request — the pool cannot hold "
                            "one full sequence; raise "
                            "MXTPU_SERVE_NUM_BLOCKS"))
                        break
                    self._preempt(victim)
                    if victim is req:
                        break               # we preempted ourselves

    def _preempt(self, req):
        """Free a runner's blocks and re-queue it (front).  Its
        generated tokens survive; re-admission re-prefills
        prompt+generated (cheap again once the prefix cache holds
        the shared blocks)."""
        freed = len(req.block_ids)
        self._in_transit = req      # out of the slot, not yet queued
        self._sched.clear(req)
        if req.block_ids:
            self.pool.free(req.block_ids)
        req.block_ids = []
        req.n_past = 0
        req.state = QUEUED
        req.preemptions += 1
        self._m_preempt.inc()
        # a preempted runner is queued again: its queue-wait clock
        # restarts here (decomposition stays truthful across cycles)
        req.enqueue_ts = time.monotonic()
        tracing.trace_event(
            "serve_preempt", rid=req.id, engine=self.engine_id,
            generated_tokens=len(req.generated), freed_blocks=freed,
            preemptions=req.preemptions)
        self._prof_async("e", "decode", req)
        self._sched.push_front(req)
        self._in_transit = None
        tracing.trace_event("serve_requeue", rid=req.id,
                            engine=self.engine_id,
                            queue_depth=len(self._sched.waiting))
        self._prof_async("b", "queue_wait", req)

    def _decode_once(self, events):
        """One batched decode step + the per-iteration token read.

        Watchdog: with ``MXTPU_SERVE_STEP_TIMEOUT`` > 0, an
        iteration whose decode (injection included — serve:step:N:
        hang is the test vector) runs past the budget logs loudly,
        records ``serve_step_overrun`` and dumps the flight recorder
        (``MXTPU_TRACE_DUMP``).  Detection, not interruption: a
        wedged device call cannot be cancelled portably — converting
        the overrun into a post-mortem is this layer's job, killing
        the process is the heartbeat monitor's."""
        import jax
        import jax.numpy as jnp
        t_step = time.monotonic()
        resilience.inject("serve", "step")
        B, MB = self.max_batch, self.max_blocks
        tokens = np.zeros(B, np.int32)
        npast = np.zeros(B, np.int32)
        tables = np.zeros((B, MB), np.int32)
        slots = self._sched.slots
        for i, req in enumerate(slots):
            if req is None:
                continue
            tokens[i] = req.generated[-1]
            npast[i] = req.n_past
            tables[i, :len(req.block_ids)] = req.block_ids
        fn = self._get_step_fn()
        with telemetry.span("serve_decode"):
            self._kpools, self._vpools, nxt, logits = fn(
                self._wts, self._kpools, self._vpools,
                jnp.asarray(tables), jnp.asarray(npast),
                jnp.asarray(tokens))
            # completion barrier (see _admit): the token read below
            # already serializes the loop; waiting on the donated
            # pools too keeps the NEXT dispatch off the slow path
            jax.block_until_ready(self._kpools)
        dt_step = time.monotonic() - t_step
        if self.step_timeout > 0 and dt_step > self.step_timeout:
            tracing.trace_event(
                "serve_step_overrun", engine=self.engine_id,
                seconds=round(dt_step, 6), budget=self.step_timeout,
                running=self._sched.n_running())
            get_logger().warning(
                "serving: decode step took %.3fs against the %.3fs "
                "budget (MXTPU_SERVE_STEP_TIMEOUT); flight-recorder "
                "post-mortem follows when MXTPU_TRACE_DUMP is set",
                dt_step, self.step_timeout)
            tracing.dump_on_fault("serve_step_overrun")
        toks = np.asarray(nxt)  # sync-ok: the per-iteration token read
        for i, req in enumerate(list(slots)):
            if req is None:
                continue
            # perf ledger: analytic FLOPs for this token at its
            # context length (host arithmetic; no device reads)
            self._perf_flops += self.model.decode_flops_per_token(
                req.n_past)
            self._perf_tokens += 1
            req.n_past += 1
            if self.keep_logits:
                req.logits = logits[i]
            self._append_token(req, int(toks[i]), events)

    def _append_token(self, req, tok, events):
        """Record one emitted token; retire the request when its
        budget or EOS is reached."""
        now = time.monotonic()
        if req.first_token_ts is None:
            req.first_token_ts = now
            self._h_ttft.observe(now - req.submit_ts)
            # serving-side anomaly watchdog: TTFT drift (host floats)
            telemetry.anomaly_watch("serving").observe(
                {"ttft": now - req.submit_ts})
            tracing.trace_event(
                "serve_first_token", rid=req.id,
                engine=self.engine_id,
                ttft_s=round(now - req.submit_ts, 6),
                queue_wait_s=round(req.queue_wait_s, 6),
                prefill_s=round(req.prefill_s, 6))
        else:
            self._h_tok.observe(now - req.last_token_ts)
            telemetry.anomaly_watch("serving").observe(
                {"token_latency": now - req.last_token_ts})
        req.last_token_ts = now
        req.generated.append(tok)
        self._m_tokens.inc()
        events.append((req, tok))
        if (len(req.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)):
            self._retire(req)

    # ----------------------------------------------- terminal paths
    def _close_wait(self, req, now):
        """Close the open queue-wait segment of a QUEUED request
        (observability parity: every terminal path records its wait,
        however it died).  Returns the request's open async phase —
        ``queue_wait`` for queued requests, ``decode`` for running
        ones (admitted requests opened decode at prefill end)."""
        if req.state == QUEUED:
            wait = now - req.enqueue_ts
            req.queue_wait_s += wait
            self._h_wait.observe(wait)
            return "queue_wait"
        return "decode"

    def _release(self, req, now):
        """Shared terminal release: slot cleared and every pool
        block freed in the SAME iteration the terminal was decided,
        so the next admission sees the memory."""
        open_phase = self._close_wait(req, now)
        self._sched.clear(req)
        if req.block_ids:
            self.pool.free(req.block_ids)
        req.block_ids = []
        req.finish_ts = now
        return open_phase

    def _finalize(self, req):
        """Terminal bookkeeping every exit path funnels through:
        exactly one summary, one completed entry, one per-state
        count, and the reap arm-counters released."""
        with self._submit_lock:
            self._live.pop(req.id, None)
            # release only counts cancel() actually took: the
            # stream-abandon flag never bumps the counter, and an
            # uncounted decrement here would steal — and starve —
            # another request's pending cancel behind the reap gate
            if req.cancel_counted and self._cancels_pending > 0:
                self._cancels_pending -= 1
            if (req.ttft_deadline_ts is not None
                    or req.deadline_ts is not None) \
                    and self._deadlines_armed > 0:
                self._deadlines_armed -= 1
            # under the lock: _reject() bumps the same dict from
            # client threads — racing read-modify-writes would
            # silently lose terminal counts
            self._terminal_counts[req.state] = \
                self._terminal_counts.get(req.state, 0) + 1
        self._completed.append(req)
        self._req_summaries.append(self._request_summary(req))

    def _retire(self, req):
        now = time.monotonic()
        self._release(req, now)
        req.state = FINISHED
        tracing.trace_event(
            "serve_retire", rid=req.id, engine=self.engine_id,
            tokens_generated=len(req.generated),
            preemptions=req.preemptions,
            queue_wait_s=round(req.queue_wait_s, 6),
            prefill_s=round(req.prefill_s, 6))
        self._terminal_async(req, "decode")
        self._finalize(req)

    def _fail(self, req, exc):
        """Evict a poisoned or unservable request without touching
        batchmates (queued requests close their wait segment, running
        ones their decode phase).

        Observability parity with retirement: the queue wait is
        recorded (an admission-time eviction would otherwise leave
        the wait histogram blind to the request), a terminal
        ``serve_evict`` event closes the lifecycle, and the flight
        recorder dumps (MXTPU_TRACE_DUMP) — an eviction is a fault,
        and the ring holds the request's whole story."""
        get_logger().warning(
            "serving: evicting request %s after injected/terminal "
            "fault: %s", req.id, exc)
        now = time.monotonic()
        open_phase = self._release(req, now)
        req.state = FAILED
        req.error = exc
        self._m_evict.inc()
        tracing.trace_event(
            "serve_evict", rid=req.id, engine=self.engine_id,
            error=str(exc),
            tokens_generated=len(req.generated),
            queue_wait_s=round(req.queue_wait_s, 6),
            preemptions=req.preemptions)
        self._terminal_async(req, open_phase)
        self._finalize(req)
        tracing.dump_on_fault("serving_eviction")

    def _expire(self, req, why, now):
        """Terminal ``expired``: the request's TTFT or total
        deadline passed.  Partial output is retained on the handle;
        ``req.error`` carries a typed DeadlineExceededError."""
        open_phase = self._release(req, now)
        req.state = EXPIRED
        req.error = resilience.DeadlineExceededError(
            f"serving request {req.id} missed its {why} deadline "
            f"after {len(req.generated)} generated token(s)")
        self._m_expired.inc()
        tracing.trace_event(
            "serve_expire", rid=req.id, engine=self.engine_id,
            why=why, tokens_generated=len(req.generated),
            queue_wait_s=round(req.queue_wait_s, 6),
            preemptions=req.preemptions)
        self._terminal_async(req, open_phase)
        self._finalize(req)

    def _cancel_now(self, req, now):
        """Terminal ``cancelled``: honor a client cancellation.
        Partial output retained; blocks freed this iteration."""
        open_phase = self._release(req, now)
        req.state = CANCELLED
        self._m_cancelled.inc()
        tracing.trace_event(
            "serve_cancel", rid=req.id, engine=self.engine_id,
            tokens_generated=len(req.generated),
            queue_wait_s=round(req.queue_wait_s, 6),
            preemptions=req.preemptions)
        self._terminal_async(req, open_phase)
        self._finalize(req)

    @staticmethod
    def _verdict(req, now):
        """Why a live request must leave the engine now, or None.
        Cancellation wins over expiry (the client already hung up);
        the TTFT deadline only binds before the first token."""
        if req.cancel_requested:
            return "cancel"
        if req.deadline_ts is not None and now >= req.deadline_ts:
            return "total"
        if req.first_token_ts is None \
                and req.ttft_deadline_ts is not None \
                and now >= req.ttft_deadline_ts:
            return "ttft"
        return None

    @staticmethod
    def _next_deadline(req):
        """Earliest future stamp at which ``req`` could expire, or
        +inf.  A stale TTFT stamp after the first token only makes
        the next sweep fire early — the sweep re-verdicts, so early
        is harmless and late is impossible."""
        nxt = float("inf")
        if req.deadline_ts is not None:
            nxt = req.deadline_ts
        if req.first_token_ts is None \
                and req.ttft_deadline_ts is not None:
            nxt = min(nxt, req.ttft_deadline_ts)
        return nxt

    def _reap(self):
        """Honor pending cancellations and blown deadlines — queued
        and running alike — freeing blocks/slots in the same
        iteration.  Two guards keep this off the decode hot path:
        the arm counters (no deadline armed, no cancel pending = one
        integer test) and the earliest-armed-deadline stamp (armed
        but not yet due = one clock read).  Expired/cancelled queued
        requests are REMOVED in place — never pop-all-and-re-push,
        whose empty-queue window a concurrent ``submit()`` admission
        check or a SIGTERM-time ``snapshot()`` would observe."""
        flagged = self._abandon_flagged
        if not (self._cancels_pending or flagged
                or self._deadlines_armed):
            return
        now = time.monotonic()
        if not (self._cancels_pending or flagged) \
                and now < self._deadline_next:
            return
        self._abandon_flagged = False
        with self._submit_lock:
            # reset BEFORE the walk: a submit() arming an earlier
            # deadline mid-sweep mins into this, and the final store
            # below mins back — neither update can be lost
            self._deadline_next = float("inf")
        nxt = float("inf")
        # safe_list: a client thread's submit() may append while we
        # walk (a bare list() of a mutating deque raises); removal
        # serializes against that append under the submit lock
        for req in tracing.safe_list(self._sched.waiting):
            why = self._verdict(req, now)
            if why is None:
                nxt = min(nxt, self._next_deadline(req))
                continue
            with self._submit_lock:
                removed = self._sched.remove_waiting(req)
            if removed:
                if why == "cancel":
                    self._cancel_now(req, now)
                else:
                    self._expire(req, why, now)
        for req in list(self._sched.slots):
            if req is None:
                continue
            why = self._verdict(req, now)
            if why == "cancel":
                self._cancel_now(req, now)
            elif why is not None:
                self._expire(req, why, now)
            else:
                nxt = min(nxt, self._next_deadline(req))
        with self._submit_lock:
            self._deadline_next = min(self._deadline_next, nxt)

    # -------------------------------------------------- observability
    def _prof_async(self, ph, name, req):
        """Emit one chrome-tracing async (b/e) event for a request
        phase when the profiler is running; each request id is an
        async track, placed on a named serving lane.  Lane choice is
        a function of the PHASE, not of ``req.slot`` at emission
        time — slot is nulled by ``Scheduler.clear`` before terminal
        events fire, and every phase of one request must land on one
        lane: ``request``/``queue_wait`` live on the queue lane,
        compute phases (``prefill``/``decode``) on the slot of the
        request's FIRST admission (``last_slot``, pinned in
        ``Scheduler.place`` and never cleared — re-admission into a
        different slot must not split the track)."""
        from .. import profiler
        prof = profiler._profiler
        if not prof.running:
            return
        if name in ("request", "queue_wait") or req.last_slot is None:
            lane = profiler.SERVE_QUEUE_LANE
        else:
            lane = profiler.SERVE_SLOT_LANE0 + req.last_slot
        prof.add_async_event(name,
                             f"req{self.engine_id}.{req.id}", ph,
                             category="serving", lane=lane)

    def _terminal_async(self, req, open_phase):
        """Close a request's open async phases at its terminal
        transition.  ``open_phase`` is the phase still open at that
        point: ``decode`` for retirement (opened at the last
        admission) and for any terminal that catches the request
        RUNNING (expiry, cancellation, the single-runner pool-
        exhaustion failure in ``_grow``); ``queue_wait`` for a
        terminal that catches it QUEUED — ``_close_wait`` decides
        from the request's state."""
        self._prof_async("e", open_phase, req)
        self._prof_async("e", "request", req)

    @staticmethod
    def _request_summary(req):
        """One request's TTFT decomposition for :meth:`stats`."""
        ttft = (req.first_token_ts - req.submit_ts
                if req.first_token_ts is not None else None)
        decode = (req.last_token_ts - req.first_token_ts
                  if req.first_token_ts is not None
                  and req.last_token_ts is not None else None)
        return {
            "id": req.id, "state": req.state,
            "prompt_tokens": len(req.prompt),
            "tokens_generated": len(req.generated),
            "preemptions": req.preemptions,
            "queue_wait_s": round(req.queue_wait_s, 6),
            "prefill_s": round(req.prefill_s, 6),
            "ttft_s": round(ttft, 6) if ttft is not None else None,
            "decode_s": (round(decode, 6)
                         if decode is not None else None),
            "error": (str(req.error)
                      if req.error is not None else None),
        }

    def stats(self):
        """Engine observability snapshot: per-request lifecycle
        summaries (terminal requests from the bounded summary ring,
        live ones in flight), trace/compile counts, and pool state.
        Host-side bookkeeping only — no device access; safe to call
        from a monitoring thread while the engine runs
        (tracing.safe_list absorbs concurrent deque mutation)."""
        live = [self._request_summary(r)
                for r in tracing.safe_list(self._sched.waiting)
                + self._sched.running()]
        return {
            "requests": tracing.safe_list(self._req_summaries),
            "live": live,
            "trace_counts": dict(self.trace_counts),
            "batch_occupancy":
                self._sched.n_running() / self.max_batch,
            "pool_utilization": self.pool.utilization(),
            # SLO/survival view: how every request ended
            # ('rejected' counts submissions shed at the door),
            # plus the admission controller's live pressure
            "terminal_counts": dict(self._terminal_counts),
            "queue_depth": len(self._sched.waiting),
            "queued_tokens": self._sched.queued_tokens,
            "draining": self._draining,
        }
