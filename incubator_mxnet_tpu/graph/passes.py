"""Graph-optimization passes and the PassManager.

Role analog of nnvm's pass registry (ref: include/nnvm/pass.h,
src/pass/*.cc) pointed in the Relay/TVM direction: named passes with
declared ordering dependencies run over the :class:`~.ir.Graph` copy
of a Symbol DAG between symbol construction and executor bind.  XLA
already fuses and schedules the *compiled* graph; these passes shrink
and normalize the *traced* graph, so tracing, jaxpr construction and
XLA's own pipeline all see fewer nodes (ROADMAP item 4 — serving
wants whole-graph capture, MFU wants fusion control).

Level contract (``MXTPU_GRAPH_OPT``):

- ``0`` — pipeline off; ``optimize_symbol`` returns the input Symbol.
- ``1`` (default) — safe structural passes: identity elimination,
  transpose-pair elimination, constant folding, common-subexpression
  elimination, dead-node pruning.  Bitwise output-preserving.
- ``2`` — adds elementwise-chain pre-fusion (``fuse.py``): adjacent
  pure elementwise ops collapse into one fused callable, so the
  traced graph hands XLA a single region per chain.

Every pass reports a node delta; the pipeline publishes
``graph_passes_total`` / ``graph_nodes_eliminated_total`` counters
and times itself under the ``graph_optimize`` span.
"""
import numpy as np

from .. import telemetry
from ..ops.registry import OpDef
from ..symbol.symbol import _Node
from ..utils.env import get_env
from .ir import Graph, entry_key, freeze_params

__all__ = ["GraphPass", "PassManager", "register_pass", "PASSES",
           "default_pass_names", "optimize_symbol", "stamp_rng_indices",
           "CONST_OP", "FOLD_MAX_ELEMENTS"]

# Constant folding materializes values at bind time; cap the baked
# size so a folded subtree never bloats the executable with a huge
# literal (XLA would re-fold bigger ones on device anyway).
FOLD_MAX_ELEMENTS = 65536

# Op names whose nodes are constant *sources* (no tensor inputs,
# value fully determined by static params).
_CONST_SOURCES = ("_zeros", "_ones", "_full", "_arange", "_eye")


def _const_fn(value=None):
    """Replay a folded constant (value baked as a static param)."""
    import jax.numpy as jnp
    return jnp.asarray(value)


# Internal op for folded constants.  Deliberately NOT registered in
# the global OPS table: optimized graphs are bind-internal and must
# never round-trip through tojson/load_json.
CONST_OP = OpDef("_graph_const", _const_fn, differentiable=False)


def stamp_rng_indices(graph):
    """Pin each rng-consuming node's fold-in index as an attr.

    ``build_graph_fn`` folds the forward rng key per rng node in topo
    order; a pass that removes *other* nodes must not shift those
    indices, or optimized and unoptimized graphs would draw different
    randomness from the same key.  Stamping the pre-optimization
    index makes the stream invariant under every rewrite (passes
    never touch rng nodes themselves).
    """
    idx = 0
    for node in graph.topo():
        if node.op is not None and node.op.needs_rng:
            node.attrs["__rng_index__"] = str(idx)
            idx += 1


class GraphPass:
    """A named rewrite over a :class:`Graph`.

    Subclasses set ``name`` (unique), optionally ``after`` (names of
    passes that must run earlier when both are selected), and
    implement :meth:`run` returning an optional dict of pass-specific
    stats (e.g. ``{"folded": 3}``).
    """

    name = None
    after = ()

    def run(self, graph):
        raise NotImplementedError


PASSES = {}


def register_pass(cls):
    """Class decorator adding a pass to the registry."""
    if not cls.name:
        raise ValueError("pass needs a name")
    if cls.name in PASSES:
        raise ValueError(f"pass '{cls.name}' registered twice")
    PASSES[cls.name] = cls
    return cls


def _is_pure(op):
    """Ops safe for value-keyed rewrites: deterministic, no mode
    branch, no aux-state writeback."""
    return (op is not None and not op.needs_rng and not op.needs_mode
            and op.num_aux == 0)


# Ops whose output dtype is always inexact (float): scalar-identity
# elimination is only dtype-safe downstream of these — `int32 * 1.0`
# promotes to float32, so removing the node on an int input would
# change the output dtype (caught in review; regression-tested).
FLOAT_RESULT_OPS = frozenset({
    "tanh", "sigmoid", "exp", "expm1", "log", "log1p", "log2",
    "log10", "sqrt", "rsqrt", "cbrt", "rcbrt", "erf", "erfinv",
    "sin", "cos", "tan", "sinh", "cosh", "arctan", "arcsinh",
    "softmax", "log_softmax", "softrelu", "softsign", "gamma",
    "gammaln", "radians", "degrees", "reciprocal",
    "_div_scalar", "_rdiv_scalar", "mean", "norm", "LayerNorm",
    "InstanceNorm", "BatchNorm", "L2Normalization",
})

# Activation produces float only for the saturating kinds;
# act_type='relu' preserves integer dtypes.
_FLOAT_ACT_TYPES = frozenset({"sigmoid", "tanh", "softrelu",
                              "softsign"})


@register_pass
class EliminateIdentity(GraphPass):
    """Drop exact no-op nodes: ``_copy``/``identity`` always, and the
    scalar identities mul/div by 1 when the input is provably float
    (value-exact in IEEE754; on integer inputs ``* 1.0`` PROMOTES the
    dtype, so those stay).  Add/sub of 0 is never eliminated — it
    rewrites -0.0 to +0.0."""

    name = "eliminate_identity"

    _SCALAR_ONE = ("_mul_scalar", "_div_scalar")
    # scalar-op nodes with a python-float param promote any input to
    # float, so they are float producers too
    _SCALAR_PROMOTING = ("_mul_scalar", "_div_scalar", "_plus_scalar",
                         "_minus_scalar", "_rminus_scalar",
                         "_rdiv_scalar", "_power_scalar",
                         "_rpower_scalar")

    @classmethod
    def _produces_float(cls, node):
        if node.op is None:
            return False
        if node.op.name in FLOAT_RESULT_OPS:
            return True
        if node.op.name == "Activation":
            return node.params.get("act_type") in _FLOAT_ACT_TYPES
        return (node.op.name in cls._SCALAR_PROMOTING
                and isinstance(node.params.get("scalar", 1.0), float))

    def run(self, graph):
        mapping = {}

        def resolve(entry):
            while entry_key(entry) in mapping:
                entry = mapping[entry_key(entry)]
            return entry

        for node in graph.topo():
            if node.op is None or len(node.inputs) != 1:
                continue
            opname = node.op.name
            if opname == "_copy":
                mapping[(id(node), 0)] = resolve(node.inputs[0])
            elif opname in self._SCALAR_ONE:
                scalar = node.params.get("scalar", 1.0)
                inode, _ = resolve(node.inputs[0])
                if isinstance(scalar, (int, float)) \
                        and not isinstance(scalar, bool) \
                        and float(scalar) == 1.0 \
                        and self._produces_float(inode):
                    mapping[(id(node), 0)] = resolve(node.inputs[0])
        graph.apply_replacements(mapping)
        return {"removed": len(mapping)}


@register_pass
class EliminateTransposePairs(GraphPass):
    """Compose back-to-back ``transpose`` nodes; a pair whose
    permutations cancel is removed entirely."""

    name = "eliminate_transpose_pairs"
    after = ("eliminate_identity",)

    @staticmethod
    def _axes(node):
        axes = node.params.get("axes", ())
        axes = tuple(int(a) for a in axes) if axes else ()
        return axes or None      # empty = reverse; rank unknown here

    def run(self, graph):
        cancelled = merged = 0
        changed = True
        while changed:
            changed = False
            for node in graph.topo():
                if node.op is None or node.op.name != "transpose":
                    continue
                inner, iidx = node.inputs[0]
                if iidx != 0 or inner.op is None \
                        or inner.op.name != "transpose":
                    continue
                outer_ax, inner_ax = self._axes(node), self._axes(inner)
                if outer_ax is None or inner_ax is None \
                        or len(outer_ax) != len(inner_ax):
                    continue
                composed = tuple(inner_ax[a] for a in outer_ax)
                if composed == tuple(range(len(composed))):
                    graph.replace_entry((node, 0), inner.inputs[0])
                    cancelled += 1
                else:
                    node.inputs[0] = inner.inputs[0]
                    node.params["axes"] = composed
                    merged += 1
                changed = True
        return {"cancelled_pairs": cancelled, "merged": merged}


@register_pass
class FoldConstants(GraphPass):
    """Evaluate subtrees rooted only in constant sources
    (``_zeros``/``_ones``/``_full``/``_arange``/``_eye``) at bind
    time and bake the result as one ``_graph_const`` node."""

    name = "fold_constants"
    after = ("eliminate_identity", "eliminate_transpose_pairs")

    def run(self, graph):
        import jax.numpy as jnp
        values = {}       # id(node) -> np.ndarray
        mapping = {}      # batched entry rewrites (one final sweep)
        folded = 0
        for node in graph.topo():
            op = node.op
            if op is None:
                continue
            if op is CONST_OP:
                values[id(node)] = node.params["value"]
                continue
            if op.name in _CONST_SOURCES:
                try:
                    values[id(node)] = np.asarray(op.fn(**node.params))
                except Exception:       # dynamic param — leave as-is
                    continue
                continue
            if not _is_pure(op) or not node.inputs:
                continue
            if op.n_outputs(node.params) != 1:
                continue
            in_vals = [values.get(id(n)) for n, i in node.inputs]
            if any(v is None for v in in_vals) \
                    or any(i != 0 for _, i in node.inputs):
                continue
            try:
                out = op.fn(*[jnp.asarray(v) for v in in_vals],
                            **node.params)
                out = np.asarray(out)
            except Exception:
                continue
            if out.size > FOLD_MAX_ELEMENTS:
                continue
            const = _Node(CONST_OP, node.name + "_const",
                          params={"value": out})
            graph.nodes.append(const)
            mapping[(id(node), 0)] = (const, 0)
            values[id(node)] = out     # downstream folds see through
            folded += 1
        graph.apply_replacements(mapping)
        return {"folded": folded}


@register_pass
class EliminateCommonSubexpressions(GraphPass):
    """Merge structurally identical pure nodes (same op, same frozen
    params, same input entries) into one — the NNVM/Relay CSE pass.
    Variables are never merged; rng/mode/aux ops are excluded (two
    dropout nodes draw different keys by design)."""

    name = "eliminate_common_subexpressions"
    after = ("fold_constants",)

    def run(self, graph):
        seen = {}
        mapping = {}      # batched entry rewrites (one final sweep)
        merged = 0

        def resolve(entry):
            while entry_key(entry) in mapping:
                entry = mapping[entry_key(entry)]
            return entry

        for node in graph.topo():
            if node.op is None or not _is_pure(node.op):
                continue
            frozen = freeze_params(node.params)
            if frozen is None:
                continue
            key = (node.op.name, frozen,
                   tuple(entry_key(resolve(e)) for e in node.inputs))
            rep = seen.get(key)
            if rep is None:
                seen[key] = node
            elif rep is not node:
                for i in range(node.op.n_outputs(node.params)):
                    mapping[(id(node), i)] = (rep, i)
                merged += 1
        graph.apply_replacements(mapping)
        return {"merged": merged}


@register_pass
class PruneDeadNodes(GraphPass):
    """Sweep nodes no longer reachable from any head (orphans left by
    the rewrite passes).  Reachable nodes — in particular every head
    — are never dropped: the pass is an intersection with the live
    set, nothing more."""

    name = "prune_dead_nodes"
    after = ("eliminate_identity", "eliminate_transpose_pairs",
             "fold_constants", "eliminate_common_subexpressions",
             "fuse_elemwise")

    def run(self, graph):
        live = {id(n) for n in graph.topo()}
        before = len(graph.nodes)
        graph.nodes = [n for n in graph.nodes if id(n) in live]
        return {"swept": before - len(graph.nodes)}


def default_pass_names(level):
    names = ["eliminate_identity", "eliminate_transpose_pairs",
             "fold_constants", "eliminate_common_subexpressions"]
    if level >= 2:
        names.append("fuse_elemwise")
    names.append("prune_dead_nodes")
    return names


class PassManager:
    """Runs a set of named passes in dependency order with per-pass
    node-delta stats (the ``nnvm::ApplyPasses`` analog)."""

    def __init__(self, pass_names):
        from . import fuse                      # registers fuse pass
        del fuse
        unknown = [n for n in pass_names if n not in PASSES]
        if unknown:
            raise KeyError(f"unknown graph passes {unknown}; "
                           f"registered: {sorted(PASSES)}")
        self._passes = [PASSES[n]() for n in
                        self._order(list(pass_names))]

    @staticmethod
    def _order(names):
        """Stable topological order honoring each pass's ``after``."""
        selected = set(names)
        placed, out = set(), []
        remaining = list(names)
        while remaining:
            progressed = False
            for n in list(remaining):
                deps = [d for d in PASSES[n].after
                        if d in selected and d != n]
                if all(d in placed for d in deps):
                    out.append(n)
                    placed.add(n)
                    remaining.remove(n)
                    progressed = True
            if not progressed:
                raise ValueError(
                    f"graph pass dependency cycle among {remaining}")
        return out

    @property
    def pass_names(self):
        return [p.name for p in self._passes]

    def run(self, graph):
        """Apply all passes; returns the pipeline report."""
        report = {"nodes_before": graph.n_nodes(), "passes": []}
        for p in self._passes:
            before = graph.n_nodes()
            extra = p.run(graph) or {}
            after = graph.n_nodes()
            telemetry.counter("graph_passes_total").inc()
            if before > after:
                telemetry.counter(
                    "graph_nodes_eliminated_total").inc(before - after)
            entry = {"pass": p.name, "nodes_before": before,
                     "nodes_after": after}
            entry.update(extra)
            report["passes"].append(entry)
        report["nodes_after"] = graph.n_nodes()
        return report


def optimize_symbol(symbol, level=None, pass_names=None):
    """Run the pipeline over a Symbol; returns ``(symbol, report)``.

    ``level`` defaults to ``MXTPU_GRAPH_OPT`` (0 = off, 1 = safe
    passes, 2 = + elementwise pre-fusion).  The input Symbol is never
    mutated; at level 0 it is returned as-is.  The returned Symbol is
    bind-internal: it may contain ``_graph_const``/fused nodes that do
    not round-trip through ``tojson``.
    """
    if level is None:
        level = get_env("MXTPU_GRAPH_OPT")
    level = int(level)
    if level <= 0:
        return symbol, {"level": 0, "nodes_before": None,
                        "nodes_after": None, "passes": []}
    with telemetry.span("graph_optimize"):
        graph = Graph.from_symbol(symbol)
        stamp_rng_indices(graph)
        pm = PassManager(pass_names or default_pass_names(level))
        report = pm.run(graph)
        report["level"] = level
        return graph.to_symbol(), report
