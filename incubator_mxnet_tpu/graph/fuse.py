"""Elementwise-chain pre-fusion (``MXTPU_GRAPH_OPT=2``).

Role analog of the reference's fused elemwise segments and TVM's
operator fusion ("TVM: An Automated End-to-End Optimizing Compiler
for Deep Learning", PAPERS.md "Operator Fusion in XLA"): maximal
single-consumer chains of pure elementwise ops collapse into one
:class:`FusedOp` node whose ``fn`` replays the member ops in order.
Tracing the fused callable emits the exact same jax primitives in
the exact same order as the unfused chain — outputs are bitwise
identical — but the traced graph, the jaxpr, and every graph-level
consumer (placement, serving capture, node-count telemetry) see one
region instead of N nodes.

Only shape-preserving-composable, stateless ops fuse: anything with
rng, train/eval mode branches, aux-state writeback, or multiple
outputs stays a chain breaker.
"""
from ..ops import elemwise as _ew
from ..symbol.symbol import _Node
from .ir import entry_key
from .passes import GraphPass, register_pass

__all__ = ["FusedOp", "ELEMWISE_OPS", "FuseElemwise"]


def _elemwise_names():
    """Canonical op names with purely elementwise compute, derived
    from the op tables in ``ops.elemwise`` so the set cannot drift
    from the registry."""
    names = set(_ew._UNARY)
    names |= {"broadcast_" + n for n in _ew._BINARY}
    names |= {"broadcast_" + n for n in _ew._CMP}
    names |= {"_" + n for n in _ew._CMP}
    names |= set(_ew._SCALAR)
    names |= {"broadcast_logical_and", "broadcast_logical_or",
              "broadcast_logical_xor"}
    names |= {"gamma", "softrelu", "smooth_l1", "logical_not",
              "add_n", "elemwise_addto", "_copy", "BlockGrad",
              "clip", "Activation", "where", "zeros_like",
              "ones_like", "Cast", "amp_cast"}
    return frozenset(names)


ELEMWISE_OPS = _elemwise_names()


class FusedOp:
    """A synthesized op replaying an elementwise chain.

    Duck-types the ``OpDef`` surface the executor reads (``fn``,
    ``n_outputs``, the mode/rng/aux flags).  Deliberately not
    registered in the global OPS table: fused graphs are
    bind-internal and never serialize.
    """

    variadic = True
    needs_mode = False
    needs_rng = False
    num_aux = 0
    aux_names = ()
    arg_names = ()
    differentiable = True
    param_defaults = {}

    def __init__(self, steps, name):
        # steps: [(OpDef, params, [("x", ext_idx) | ("c", chain_idx)])]
        self.steps = steps
        self.name = name
        self.doc = "fused elementwise chain: " + " -> ".join(
            op.name for op, _, _ in steps)
        self.fn = self._make_fn()

    def _make_fn(self):
        steps = self.steps

        def fused(*inputs):
            env = []
            for op, params, spec in steps:
                vals = [inputs[i] if tag == "x" else env[i]
                        for tag, i in spec]
                env.append(op.fn(*vals, **params))
            return env[-1]
        fused.__name__ = self.name
        return fused

    def n_outputs(self, params):
        return 1

    def __repr__(self):
        return f"FusedOp({self.name}, {len(self.steps)} ops)"


def _fusible(node):
    op = node.op
    return (op is not None and op.name in ELEMWISE_OPS
            and not op.needs_rng and not op.needs_mode
            and op.num_aux == 0 and op.n_outputs(node.params) == 1)


@register_pass
class FuseElemwise(GraphPass):
    """Collapse single-consumer chains (length >= 2) of elementwise
    ops into one FusedOp node."""

    name = "fuse_elemwise"
    after = ("eliminate_identity", "eliminate_transpose_pairs",
             "fold_constants", "eliminate_common_subexpressions")

    def run(self, graph):
        consumers = graph.consumers()
        in_chain = set()
        chains = []
        for node in graph.topo():
            if id(node) in in_chain or not _fusible(node):
                continue
            chain = [node]
            cur = node
            while True:
                cons = consumers.get(id(cur), [])
                if len(cons) != 1:
                    break
                nxt, _slot = cons[0]
                if nxt is None or id(nxt) in in_chain \
                        or not _fusible(nxt):
                    break
                chain.append(nxt)
                cur = nxt
            if len(chain) >= 2:
                chains.append(chain)
                in_chain.update(id(n) for n in chain)
        fused_nodes = 0
        for chain in chains:
            self._fuse(graph, chain)
            fused_nodes += len(chain)
        return {"chains": len(chains), "ops_fused": fused_nodes}

    @staticmethod
    def _fuse(graph, chain):
        chain_pos = {id(n): k for k, n in enumerate(chain)}
        external, ext_index = [], {}
        steps = []
        for n in chain:
            spec = []
            for inode, iidx in n.inputs:
                pos = chain_pos.get(id(inode))
                if pos is not None and iidx == 0:
                    spec.append(("c", pos))
                else:
                    k = entry_key((inode, iidx))
                    if k not in ext_index:
                        ext_index[k] = len(external)
                        external.append((inode, iidx))
                    spec.append(("x", ext_index[k]))
            steps.append((n.op, dict(n.params), spec))
        tail = chain[-1]
        op = FusedOp(steps, f"{tail.name}_fused{len(chain)}")
        fused = _Node(op, op.name, inputs=external,
                      attrs=dict(tail.attrs))
        graph.nodes.append(fused)
        graph.replace_node(tail, fused)
