"""``graph``: the optimization layer between Symbol and Executor.

A pass pipeline over the symbolic ``_Node`` IR (Relay/NNVM direction;
ROADMAP item 4) plus :class:`CachedOp`, the trace-once replay cache
behind ``HybridBlock.hybridize()``.  ``Executor.bind`` routes every
non-placed graph through :func:`optimize_symbol` under
``MXTPU_GRAPH_OPT`` (0 = off, 1 = safe passes, 2 = + elementwise
pre-fusion).  See docs/graph_passes.md.
"""
from .ir import Graph
from .passes import (GraphPass, PassManager, PASSES, register_pass,
                     default_pass_names, optimize_symbol, CONST_OP)
from .fuse import FusedOp, FuseElemwise, ELEMWISE_OPS
from .cached_op import CachedOp, UnsupportedSignatureError

__all__ = ["Graph", "GraphPass", "PassManager", "PASSES",
           "register_pass", "default_pass_names", "optimize_symbol",
           "CONST_OP", "FusedOp", "FuseElemwise", "ELEMWISE_OPS",
           "CachedOp", "UnsupportedSignatureError"]
