"""Lightweight mutable IR over the Symbol ``_Node`` DAG.

Role analog of ``nnvm::Graph`` (ref: include/nnvm/graph.h) in the
direction of Relay ("Relay: A New IR for Machine Learning
Frameworks"): a :class:`Graph` is a *copy* of the node DAG reachable
from a Symbol's heads, owned by the optimization pipeline.  Passes
mutate the copy freely (this package and ``symbol/`` are the only
places allowed to touch ``_Node`` internals — enforced by
``ci/lint.py``); the user's Symbol is never modified, and
``to_symbol()`` hands the rewritten heads back as an ordinary Symbol
the Executor can bind.

Entries are ``(node, out_index)`` pairs exactly as in
``symbol.symbol``; node identity is Python object identity.
"""
import numpy as np

from ..symbol.symbol import Symbol, _Node, _topo

__all__ = ["Graph", "freeze_params", "entry_key"]


def freeze_params(params):
    """Canonical hashable form of a node's static params.

    Lists become tuples, dicts become sorted item tuples, and array
    values (constants baked by folding) hash by dtype/shape/bytes —
    the same stable-hashing discipline as the eager ``_stable_pair``
    cache.  Returns None when a value resists canonicalization (the
    caller then skips hash-keyed rewrites for that node).
    """
    def _freeze(v):
        if isinstance(v, (list, tuple)):
            return tuple(_freeze(x) for x in v)
        if isinstance(v, dict):
            return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
        if isinstance(v, np.ndarray):
            return ("__array__", str(v.dtype), v.shape, v.tobytes())
        if hasattr(v, "dtype") and hasattr(v, "tobytes"):
            a = np.asarray(v)
            return ("__array__", str(a.dtype), a.shape, a.tobytes())
        return v
    try:
        frozen = tuple(sorted((k, _freeze(v)) for k, v in params.items()))
        hash(frozen)
        return frozen
    except (TypeError, ValueError):
        return None


def entry_key(entry):
    """Hashable identity of an (node, out_index) entry."""
    return (id(entry[0]), entry[1])


class Graph:
    """A mutable copy of the DAG under a set of head entries.

    ``nodes`` is the explicit owned-node list (the nnvm IndexedGraph
    analog): rewrites append replacement nodes to it and the
    dead-node pruning pass sweeps it back to the set reachable from
    ``heads``.  Execution always follows reachability, so a stale
    entry in ``nodes`` is bookkeeping, never a semantic leak.
    """

    def __init__(self, heads, nodes=None):
        self.heads = list(heads)   # [(node, out_idx)]
        self.nodes = list(nodes) if nodes is not None \
            else _topo(self.heads)

    # ------------------------------------------------------------ build
    @classmethod
    def from_symbol(cls, symbol):
        """Deep-copy the reachable DAG (fresh ``_Node`` objects, shared
        ``OpDef`` references, copied params/attrs dicts)."""
        mapping = {}
        for node in _topo(symbol._heads):
            mapping[id(node)] = _Node(
                node.op, node.name,
                inputs=[(mapping[id(n)], i) for n, i in node.inputs],
                params=dict(node.params), attrs=dict(node.attrs))
        heads = [(mapping[id(n)], i) for n, i in symbol._heads]
        return cls(heads, nodes=list(mapping.values()))

    def to_symbol(self):
        return Symbol(self.heads)

    # ------------------------------------------------------------ query
    def topo(self):
        """Topological order of reachable nodes (variables included)."""
        return _topo(self.heads)

    def n_nodes(self):
        return len(self.topo())

    def consumers(self):
        """Map id(node) -> list of (consumer_node_or_None, slot).

        ``None`` as the consumer marks a head entry; ``slot`` is the
        input position (or head position for heads).
        """
        out = {}
        for node in self.topo():
            out.setdefault(id(node), [])
        for node in self.topo():
            for slot, (inp, _) in enumerate(node.inputs):
                out[id(inp)].append((node, slot))
        for pos, (node, _) in enumerate(self.heads):
            out[id(node)].append((None, pos))
        return out

    # ------------------------------------------------------------ rewrite
    def replace_entry(self, old_entry, new_entry):
        """Redirect every use of ``old_entry`` to ``new_entry``."""
        self.apply_replacements({entry_key(old_entry): new_entry})

    def apply_replacements(self, mapping):
        """Apply many entry redirects in ONE graph walk.

        ``mapping`` is {entry_key(old): new_entry}; chains (a->b with
        b itself remapped to c) are resolved transitively, so passes
        can batch every rewrite they discover and stay O(N) instead
        of paying a full walk per replacement.
        """
        if not mapping:
            return

        def resolve(entry):
            seen = set()
            k = entry_key(entry)
            while k in mapping:
                if k in seen:
                    raise ValueError(
                        f"cyclic entry replacement at {k}")
                seen.add(k)
                entry = mapping[k]
                k = entry_key(entry)
            return entry

        for node in self.topo():
            node.inputs = [resolve(e) for e in node.inputs]
        self.heads = [resolve(e) for e in self.heads]

    def replace_node(self, old, new):
        """Redirect all output entries of ``old`` to the same-index
        entries of ``new``."""
        oid = id(old)
        for node in self.topo():
            node.inputs = [(new, i) if id(n) == oid else (n, i)
                           for n, i in node.inputs]
        self.heads = [(new, i) if id(n) == oid else (n, i)
                      for n, i in self.heads]
