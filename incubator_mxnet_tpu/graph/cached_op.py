"""CachedOp: signature-keyed trace-once replay for gluon HybridBlocks.

Role analog of the reference's ``CachedOp`` (ref:
src/imperative/cached_op.cc GetForwardGraph:171, python/mxnet/gluon/
block.py _build_cache:365).  ``HybridBlock.hybridize()`` routes
``__call__`` here: the block's forward is traced ONCE per signature
``(input shapes/dtypes, canonicalized static args, train-flag)`` and
subsequent calls replay a compiled callable — no per-call Python walk
of the layer tree, no retrace.

Two trace backends, chosen per entry:

- **graph** — when ``MXTPU_GRAPH_OPT`` >= 1 and the block is
  symbol-traceable, the block is exported to a Symbol graph
  (``HybridBlock._trace_symbol``), run through the graph-optimization
  pass pipeline (``passes.optimize_symbol``), and compiled from the
  *optimized* graph.  Blocks with rng-consuming ops (dropout) skip
  this path so hybridized randomness keeps drawing the exact eager
  key stream.
- **jit** — fallback: ``jax.jit`` over the block's eager forward with
  parameter values threaded functionally (the pre-CachedOp
  ``_build_cache`` machinery), correct for every block.

Static (non-tensor) call arguments are canonicalized into the
signature with the ``_stable_pair`` hashing discipline — ``2``,
``2.0`` and ``np.float32(2.0)`` are distinct only when their
*type class* (int vs float) differs, never per-object — so a
constant argument can never force a retrace per call.  Entries live
in an LRU bounded by ``MXTPU_CACHEDOP_CAPACITY``.

Backward replays are compiled too: each entry caches a jitted
rematerializing vjp (the `_stable_pair` trade — recompute the
forward inside backward, in exchange for once-per-signature
compilation instead of per-step retracing).
"""
import threading
import time
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from .. import autograd, random_state, telemetry, tracing
from ..autograd import TapeNode
from ..ndarray.ndarray import NDArray
from ..utils.env import get_env
from ..utils.log import get_logger
from .passes import optimize_symbol

__all__ = ["CachedOp", "UnsupportedSignatureError"]


class UnsupportedSignatureError(TypeError):
    """An argument cannot participate in a replay-cache signature."""


def canonical_static(v):
    """Stable hashable form of a non-tensor argument.

    Numeric values collapse to their Python type class (``np.float32
    (2.0)`` == ``2.0`` but != ``2``), so equal constants always hit
    the same cache entry — the scalar analog of the ``_stable_pair``
    param canonicalization.
    """
    if isinstance(v, (bool, np.bool_)):
        return ("b", bool(v))
    if isinstance(v, (int, np.integer)):
        return ("i", int(v))
    if isinstance(v, (float, np.floating)):
        return ("f", float(v))
    if v is None or isinstance(v, str):
        return ("s", v)
    if isinstance(v, np.ndarray) and v.ndim == 0:
        return canonical_static(v.item())
    raise UnsupportedSignatureError(
        f"cannot key a replay cache on argument of type "
        f"{type(v).__name__}")


class _ArgsTemplate:
    """Splits call args into tensor leaves + a static skeleton.

    ``signature`` is the hashable cache key part; ``tensor_nds`` the
    NDArray leaves in traversal order; :meth:`rebuild` re-creates the
    original (possibly nested) argument structure around fresh tensor
    values for the replay closure.
    """

    __slots__ = ("signature", "tensor_nds", "_spec")

    def __init__(self, args):
        self.tensor_nds = []
        spec, sig = [], []
        for a in args:
            s, g = self._walk(a)
            spec.append(s)
            sig.append(g)
        self._spec = tuple(spec)
        self.signature = tuple(sig)

    def _walk(self, a):
        if isinstance(a, (np.ndarray, jnp.ndarray)) and \
                getattr(a, "ndim", 0) != 0:
            a = NDArray(jnp.asarray(a))
        if isinstance(a, NDArray):
            self.tensor_nds.append(a)
            return (("T", len(self.tensor_nds) - 1),
                    ("nd", tuple(a.shape), str(a._data.dtype)))
        if isinstance(a, (list, tuple)):
            walked = [self._walk(x) for x in a]
            tag = "L" if isinstance(a, list) else "U"
            return ((tag, tuple(w[0] for w in walked)),
                    (tag, tuple(w[1] for w in walked)))
        c = canonical_static(a)
        return (("S", c), ("s", c))

    @property
    def is_flat(self):
        """True when every top-level arg is a tensor or a static."""
        return all(s[0] in ("T", "S") for s in self._spec)

    def rebuild(self, tensor_vals):
        """Reassemble args with NDArray-wrapped ``tensor_vals``."""
        return _rebuild_args(self._spec, tensor_vals)

    def flat_args(self, make_tensor):
        """Build the flat argument list with ``make_tensor(i)`` filling
        tensor slots (used by symbol tracing); statics pass through as
        their canonical values."""
        out, ti = [], 0
        for tag, payload in self._spec:
            if tag == "T":
                out.append(make_tensor(ti))
                ti += 1
            elif tag == "S":
                out.append(payload[1])
            else:
                raise UnsupportedSignatureError(
                    "nested argument structures cannot be "
                    "symbol-traced")
        return out


def _rebuild_args(spec, tensor_vals):
    """Reassemble a call's argument structure around fresh tensor
    values.  Module-level so replay closures capture only the static
    ``spec`` — never the building call's input arrays (an LRU of 64
    entries must not pin 64 full input batches in memory)."""
    def _build(s):
        tag, payload = s
        if tag == "T":
            return NDArray(tensor_vals[payload])
        if tag == "S":
            return payload[1]
        seq = [_build(x) for x in payload]
        return seq if tag == "L" else tuple(seq)
    return [_build(s) for s in spec]


class _Entry:
    """One compiled signature: forward replay + cached backward."""

    __slots__ = ("mode", "jfwd", "make_bwd", "_bwd", "aux_writeback")

    def __init__(self, mode, jfwd, make_bwd, aux_writeback=None):
        self.mode = mode
        self.jfwd = jfwd
        self.make_bwd = make_bwd
        self._bwd = None
        self.aux_writeback = aux_writeback

    def bwd(self):
        if self._bwd is None:
            self._bwd = self.make_bwd()
        return self._bwd


class CachedOp:
    """Signature-keyed trace-once replay cache for one HybridBlock."""

    def __init__(self, block, capacity=None):
        self._block = block
        self._capacity = capacity if capacity is not None \
            else get_env("MXTPU_CACHEDOP_CAPACITY")
        self._entries = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self._trace_events = 0
        # resolve the registry objects once — the hit path must not
        # pay a registry lock per call
        self._hits_ctr = telemetry.counter("cachedop_cache_hits_total")
        self._misses_ctr = telemetry.counter(
            "cachedop_cache_misses_total")
        # retrace attribution: each miss records a compile event with
        # the signature diff vs the nearest cached entry, so a miss
        # storm names the dimension (shape/dtype/static/train) that
        # drives it (docs/observability.md)
        self._ledger = tracing.compile_ledger(
            f"cachedop:{block.name}")
        params = block.collect_params()
        self._param_names = sorted(params.keys())
        self._params = [params[n] for n in self._param_names]
        self._param_by_name = dict(zip(self._param_names, self._params))
        self._trainable_idx = [i for i, p in enumerate(self._params)
                               if p.grad_req != "null"]
        self._state_idx = [i for i, p in enumerate(self._params)
                           if p.grad_req == "null"]

    # ------------------------------------------------------------ stats
    @property
    def trace_count(self):
        """Python trace executions (one per signature in steady state;
        the proof behind ``cachedop_cache_misses_total``)."""
        return self._trace_events

    def stats(self):
        return {"hits": self.hits, "misses": self.misses,
                "traces": self._trace_events,
                "entries": len(self._entries),
                "modes": sorted({e.mode
                                 for e in self._entries.values()})}

    # ------------------------------------------------------------ call
    def __call__(self, *args):
        training = autograd.is_training()
        recording = autograd.is_recording()
        template = _ArgsTemplate(args)
        key = (template.signature, bool(training))
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if entry is None:
            self._misses_ctr.inc()
            self.misses += 1
            t0 = time.monotonic()
            entry = self._build_entry(template, bool(training))
            with self._lock:
                entry = self._entries.setdefault(key, entry)
                self._entries.move_to_end(key)
                while self._capacity > 0 and \
                        len(self._entries) > self._capacity:
                    self._entries.popitem(last=False)
            out = self._execute(entry, template, bool(training),
                                recording)
            # timed through the first replay: jax.jit traces lazily,
            # so build + first call is the real compile wall time
            self._ledger.record(
                _signature_components(template, training),
                time.monotonic() - t0)
            return out
        self._hits_ctr.inc()
        self.hits += 1
        return self._execute(entry, template, bool(training), recording)

    # ------------------------------------------------------------ build
    def _build_entry(self, template, training):
        level = int(get_env("MXTPU_GRAPH_OPT"))
        if level >= 1 and template.is_flat:
            try:
                return self._build_graph_entry(template, training,
                                               level)
            except Exception as exc:   # block not symbol-traceable
                get_logger().debug(
                    "CachedOp(%s): graph trace unavailable (%s: %s); "
                    "using jit replay", self._block.name,
                    type(exc).__name__, exc)
        return self._build_jit_entry(template, training)

    def _merge_params(self, tvals, others):
        pvals = [None] * len(self._params)
        for i, v in zip(self._trainable_idx, tvals):
            pvals[i] = v
        for i, v in zip(self._state_idx, others):
            pvals[i] = v
        return pvals

    # ---------------------------------------------------- graph backend
    def _build_graph_entry(self, template, training, level):
        from ..executor import build_graph_fn
        from ..symbol.symbol import Symbol, _topo
        sym, input_names = self._block._trace_symbol(template)
        if not isinstance(sym, Symbol):
            raise UnsupportedSignatureError(
                "symbol trace returned non-Symbol")
        for node in _topo(sym._heads):
            if node.op is not None and node.op.needs_rng:
                # rng nodes would draw from the graph key stream, not
                # the eager one — keep randomness identical via jit
                raise UnsupportedSignatureError(
                    f"rng op '{node.op.name}' in traced graph")
        clash = set(self._param_names) & set(input_names)
        if clash:
            raise UnsupportedSignatureError(
                f"input names collide with parameters: {sorted(clash)}")
        known = set(self._param_names) | set(input_names)
        unknown = [n for n in sym.list_inputs() if n not in known]
        if unknown:
            raise UnsupportedSignatureError(
                f"traced graph has unbound inputs {unknown}")
        opt_sym, _report = optimize_symbol(sym, level=level)
        run = build_graph_fn(opt_sym)
        param_names = self._param_names
        co = self

        def fwd(param_vals, input_vals, rng):
            co._trace_events += 1
            arg_vals = dict(zip(param_names, param_vals))
            arg_vals.update(zip(input_names, input_vals))
            outs, aux_upd = run(arg_vals, {}, rng, training)
            return list(outs), dict(aux_upd)

        jfwd = jax.jit(fwd)

        def make_bwd():
            def bwd(tvals, others, input_vals, rng, out_cts):
                def f(tv, iv):
                    pvals = self._merge_params(tv, others)
                    arg_vals = dict(zip(param_names, pvals))
                    arg_vals.update(zip(input_names, iv))
                    outs, _ = run(arg_vals, {}, rng, training)
                    return tuple(outs)
                _, vjp = jax.vjp(f, tuple(tvals), tuple(input_vals))
                tcts, icts = vjp(tuple(out_cts))
                return list(tcts), list(icts)
            return _jit_with_fallback(bwd)

        return _Entry("graph", jfwd, make_bwd,
                      aux_writeback=self._write_aux)

    def _write_aux(self, aux_upd):
        for name, val in aux_upd.items():
            p = self._param_by_name.get(name)
            if p is not None:
                p._data._data = val

    # ------------------------------------------------------ jit backend
    def _build_jit_entry(self, template, training):
        block = self._block
        param_objs = self._params
        state_idx = self._state_idx
        spec = template._spec          # structure only, no arrays
        co = self

        def run(param_vals, input_vals, rng):
            saved = [(p, p._data._data) for p in param_objs]
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(training)
            try:
                for p, v in zip(param_objs, param_vals):
                    p._data._data = v
                with random_state.key_provider(rng):
                    outs = block.forward(*_rebuild_args(spec,
                                                        input_vals))
                out_list = outs if isinstance(outs, (list, tuple)) \
                    else [outs]
                out_vals = [o._data for o in out_list]
                state_vals = [param_objs[i]._data._data
                              for i in state_idx]
            finally:
                for (p, v) in saved:
                    p._data._data = v
                autograd.set_recording(prev_rec)
                autograd.set_training(prev_train)
            return out_vals, state_vals

        def fwd(param_vals, input_vals, rng):
            co._trace_events += 1
            return run(list(param_vals), list(input_vals), rng)

        jfwd = jax.jit(fwd)

        def make_bwd():
            def bwd(tvals, others, input_vals, rng, out_cts):
                def f(tv, iv):
                    pvals = self._merge_params(tv, others)
                    out_vals, _ = run(pvals, list(iv), rng)
                    return tuple(out_vals)
                _, vjp = jax.vjp(f, tuple(tvals), tuple(input_vals))
                tcts, icts = vjp(tuple(out_cts))
                return list(tcts), list(icts)
            return _jit_with_fallback(bwd)

        return _Entry("jit", jfwd, make_bwd)

    # ---------------------------------------------------------- execute
    def _execute(self, entry, template, training, recording):
        param_vals = tuple(p.data()._data for p in self._params)
        input_nds = template.tensor_nds
        input_vals = tuple(a._data for a in input_nds)
        rng = random_state.next_key()

        out_vals, state = entry.jfwd(param_vals, input_vals, rng)
        if training:
            if entry.mode == "graph":
                entry.aux_writeback(state)
            else:
                for i, v in zip(self._state_idx, state):
                    self._params[i]._data._data = v

        out_arrays = [NDArray(v) for v in out_vals]
        if recording:
            t_idx = self._trainable_idx
            tvals = tuple(param_vals[i] for i in t_idx)
            others = tuple(param_vals[i] for i in self._state_idx)

            def node_vjp(out_cts):
                cts = list(out_cts) if isinstance(out_cts, tuple) \
                    else [out_cts]
                tcts, icts = entry.bwd()(tvals, others, input_vals,
                                         rng, tuple(cts))
                return list(tcts) + list(icts)

            node_inputs = [self._params[i]._data for i in t_idx] \
                + list(input_nds)
            avals = [(tuple(v.shape), v.dtype) for v in out_vals]
            node = TapeNode(node_vjp, node_inputs, avals,
                            f"CachedOp({self._block.name})")
            for i, arr in enumerate(out_arrays):
                arr._autograd = (node, i)
        if len(out_arrays) == 1:
            return out_arrays[0]
        return out_arrays


def _signature_components(template, training):
    """Flatten a call signature into the named-component dict the
    compile ledger diffs: tensor shapes, tensor dtypes, canonical
    static args, train flag (docs/observability.md)."""
    shapes, dtypes, statics = [], [], []

    def walk(sig):
        tag = sig[0]
        if tag == "nd":
            shapes.append(sig[1])
            dtypes.append(sig[2])
        elif tag == "s":
            statics.append(sig[1])
        else:                       # L / U nested structures
            for s in sig[1]:
                walk(s)

    for s in template.signature:
        walk(s)
    return {"shape": tuple(shapes), "dtype": tuple(dtypes),
            "static_arg": tuple(statics),
            "train_flag": bool(training)}


def _jit_with_fallback(bwd):
    """jit the backward; fall back to the uncompiled closure once if
    compilation rejects the cotangent structure (float0 cotangents of
    integer outputs are not valid jit inputs)."""
    jitted = jax.jit(bwd)
    state = {"fn": jitted}

    def call(*a):
        try:
            return state["fn"](*a)
        except (TypeError, ValueError):
            if state["fn"] is not bwd:
                state["fn"] = bwd
                return bwd(*a)
            raise
    return call
