"""Linear-algebra ops (ref: src/operator/tensor/la_op.cc — the LAPACK
bridge ops _linalg_*).  XLA provides these natively on TPU.
"""
import jax.numpy as jnp
from jax import scipy as jsp

from .registry import defop


@defop("_linalg_gemm", aliases=["linalg_gemm"])
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@defop("_linalg_gemm2", aliases=["linalg_gemm2"])
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@defop("_linalg_potrf", aliases=["linalg_potrf"])
def linalg_potrf(A):
    """Cholesky factor (lower) (ref: la_op.cc potrf)."""
    return jnp.linalg.cholesky(A)


@defop("_linalg_potri", aliases=["linalg_potri"])
def linalg_potri(A):
    """Inverse from Cholesky factor: inv(L L^T)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = jsp.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@defop("_linalg_trmm", aliases=["linalg_trmm"])
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    out = jnp.matmul(B, a) if rightside else jnp.matmul(a, B)
    return alpha * out


@defop("_linalg_trsm", aliases=["linalg_trsm"])
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    if rightside:
        if transpose:
            # solve X A^T = alpha B  ->  A X^T = alpha B^T
            xt = jsp.linalg.solve_triangular(
                A, jnp.swapaxes(B, -1, -2), lower=lower)
        else:
            # solve X A = alpha B  ->  A^T X^T = alpha B^T
            xt = jsp.linalg.solve_triangular(
                jnp.swapaxes(A, -1, -2), jnp.swapaxes(B, -1, -2),
                lower=not lower)
        return alpha * jnp.swapaxes(xt, -1, -2)
    return alpha * jsp.linalg.solve_triangular(
        A, B, lower=lower, trans=1 if transpose else 0)


@defop("_linalg_sumlogdiag", aliases=["linalg_sumlogdiag"])
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@defop("_linalg_syrk", aliases=["linalg_syrk"])
def linalg_syrk(A, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


@defop("_linalg_syevd", aliases=["linalg_syevd"], num_outputs=2)
def linalg_syevd(A):
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@defop("_linalg_gelqf", aliases=["linalg_gelqf"], num_outputs=2)
def linalg_gelqf(A):
    """LQ factorization via QR of A^T (ref: la_op.cc gelqf)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@defop("khatri_rao", variadic=True)
def khatri_rao(*args):
    """Column-wise Khatri-Rao product (ref: contrib/krprod.h)."""
    out = args[0]
    for b in args[1:]:
        out = jnp.einsum("ik,jk->ijk", out, b).reshape(
            (-1, out.shape[-1]))
    return out
