"""Ordering ops (ref: src/operator/tensor/ordering_op.cc).  The
reference used CUB device radix sort; XLA's sort HLO replaces it.
"""
import jax
import jax.numpy as jnp

from .registry import defop


@defop("sort")
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=None if axis is None else int(axis))
    if not is_ascend:
        out = jnp.flip(out, axis=-1 if axis is None else int(axis))
    return out


@defop("argsort", differentiable=False)
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    d = data if is_ascend else -data
    out = jnp.argsort(d, axis=None if axis is None else int(axis),
                      stable=True)
    return out.astype(jnp.result_type(data))


def _topk_nout(params):
    rt = params.get("ret_typ", "indices")
    return 2 if rt == "both" else 1


@defop("topk", num_outputs=_topk_nout, differentiable=False)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    """Top-k along an axis (ref: ordering_op-inl.h TopKParam)."""
    ax = data.ndim - 1 if axis is None else int(axis) % data.ndim
    k = int(k)
    d = jnp.moveaxis(data, ax, -1)
    vals, idx = jax.lax.top_k(jnp.negative(d) if is_ascend else d, k)
    if is_ascend:
        vals = jnp.negative(vals)
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax).astype(jnp.result_type(data))
    if ret_typ == "value":
        return vals
    if ret_typ == "mask":
        oh = jnp.sum(jax.nn.one_hot(
            jnp.moveaxis(idx, ax, -1).astype(jnp.int32), d.shape[-1],
            dtype=data.dtype), axis=-2)
        return jnp.moveaxis(oh, -1, ax)
    if ret_typ == "both":
        return vals, idx
    return idx
