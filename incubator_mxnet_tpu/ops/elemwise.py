"""Elementwise, scalar and comparison operators.

Covers the reference's macro-registered elemwise surface (ref:
src/operator/tensor/elemwise_unary_op_basic.cc, elemwise_unary_op_trig.cc,
elemwise_binary_op_basic.cc, elemwise_binary_scalar_op_*.cc).  On TPU
every one of these is a VPU op that XLA fuses into neighbouring
matmuls, so there is no per-op kernel: each is a one-line jnp emission.
"""
import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .registry import defop, alias

# --------------------------------------------------------------------------
# unary math (ref: MXNET_UNARY_MATH_OP sites)
# --------------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs,
    "arccos": jnp.arccos,
    "arccosh": jnp.arccosh,
    "arcsin": jnp.arcsin,
    "arcsinh": jnp.arcsinh,
    "arctan": jnp.arctan,
    "arctanh": jnp.arctanh,
    "cbrt": jnp.cbrt,
    "ceil": jnp.ceil,
    "cos": jnp.cos,
    "cosh": jnp.cosh,
    "degrees": jnp.degrees,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "fix": jnp.trunc,
    "floor": jnp.floor,
    "gammaln": jsp.gammaln,
    "log": jnp.log,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "log2": jnp.log2,
    "negative": jnp.negative,
    "radians": jnp.radians,
    "rint": jnp.rint,
    "round": jnp.round,
    "sign": jnp.sign,
    "sin": jnp.sin,
    "sinh": jnp.sinh,
    "sqrt": jnp.sqrt,
    "square": jnp.square,
    "tan": jnp.tan,
    "tanh": jnp.tanh,
    "trunc": jnp.trunc,
    "reciprocal": lambda x: 1.0 / x,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "softsign": lambda x: x / (1.0 + jnp.abs(x)),
    "erf": jsp.erf,
    "erfinv": jsp.erfinv,
}


def _make_unary(name, f):
    def _op(data, _f=f):
        return _f(data)
    _op.__name__ = name
    _op.__doc__ = f"Elementwise {name} (ref: src/operator/tensor/)."
    return _op


for _n, _f in _UNARY.items():
    defop(_n)(_make_unary(_n, _f))


@defop("gamma")
def gamma(data):
    """Gamma function Γ(x) (ref: special_functions-inl.h).

    gammaln gives log|Γ|; restore the sign for negative non-integer x,
    where Γ alternates sign between consecutive poles.
    """
    sign = jnp.where(data >= 0, 1.0,
                     1.0 - 2.0 * (jnp.abs(jnp.floor(data)) % 2))
    return sign.astype(data.dtype) * jnp.exp(jsp.gammaln(data))


@defop("_copy", aliases=["identity"])
def _copy(data):
    """Identity / copy."""
    return data + 0


@defop("BlockGrad", aliases=["stop_gradient"])
def block_grad(data):
    """Identity forward, zero gradient (ref: make_loss BlockGrad)."""
    return jax.lax.stop_gradient(data)


@defop("make_loss")
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Mark an output as a loss head (ref: src/operator/make_loss.cc)."""
    return data * 1.0


@defop("smooth_l1")
def smooth_l1(data, scalar=1.0):
    """Smooth-L1 (ref: elemwise_binary_scalar_op_extended.cc)."""
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * data * data,
                     absd - 0.5 / s2)


@defop("softrelu")
def softrelu(data):
    """log(1+exp(x)) — Activation act_type='softrelu'."""
    return jax.nn.softplus(data)


# --------------------------------------------------------------------------
# elementwise binary (same-shape) + broadcasting variants
# (ref: elemwise_binary_op_basic.cc, broadcast_reduce_op_value.cc)
# jnp broadcasts natively, so both families share one emission.
# --------------------------------------------------------------------------
_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
}

_CMP = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "lesser": jnp.less,
    "lesser_equal": jnp.less_equal,
}


def _make_binary(name, f, cmp=False):
    def _op(lhs, rhs, _f=f, _cmp=cmp):
        out = _f(lhs, rhs)
        if _cmp:
            out = out.astype(jnp.result_type(lhs))
        return out
    _op.__name__ = name
    _op.__doc__ = f"Elementwise/broadcast {name}."
    return _op


for _n, _f in _BINARY.items():
    defop("broadcast_" + _n)(_make_binary("broadcast_" + _n, _f))
for _n, _f in _CMP.items():
    defop("broadcast_" + _n)(_make_binary("broadcast_" + _n, _f, cmp=True))
    defop("_" + _n)(_make_binary("_" + _n, _f, cmp=True))

alias("broadcast_add", "elemwise_add", "_add", "_plus", "broadcast_plus")
alias("broadcast_sub", "elemwise_sub", "_sub", "_minus", "broadcast_minus")
alias("broadcast_mul", "elemwise_mul", "_mul")
alias("broadcast_div", "elemwise_div", "_div")
alias("broadcast_mod", "_mod")
alias("broadcast_power", "_power")
alias("broadcast_maximum", "_maximum", "maximum")
alias("broadcast_minimum", "_minimum", "minimum")
alias("broadcast_hypot", "_hypot")


@defop("elemwise_addto", differentiable=False)
def elemwise_addto(lhs, rhs):
    """In-place accumulate helper (kAddTo analog)."""
    return lhs + rhs


# --------------------------------------------------------------------------
# scalar family (ref: elemwise_binary_scalar_op_basic.cc)
# --------------------------------------------------------------------------
_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, s),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
}


def _make_scalar(name, f):
    def _op(data, scalar=1.0, _f=f):
        return _f(data, scalar)
    _op.__name__ = name
    _op.__doc__ = f"Scalar op {name}."
    return _op


for _n, _f in _SCALAR.items():
    defop(_n)(_make_scalar(_n, _f))


# logical
@defop("logical_not")
def logical_not(data):
    return (data == 0).astype(data.dtype)


for _n, _f in {"logical_and": jnp.logical_and,
               "logical_or": jnp.logical_or,
               "logical_xor": jnp.logical_xor}.items():
    defop("broadcast_" + _n)(_make_binary("broadcast_" + _n, _f, cmp=True))


# --------------------------------------------------------------------------
# n-ary
# --------------------------------------------------------------------------
@defop("add_n", aliases=["ElementWiseSum", "_sparse_ElementWiseSum",
                         "_sparse_add_n"], variadic=True)
def add_n(*args):
    """Sum of N tensors (ref: elemwise_sum.cc)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out
