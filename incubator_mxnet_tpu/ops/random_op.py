"""Random sampling ops (ref: src/operator/random/sample_op.cc,
multisample_op.cc, sample_multinomial_op.cc).

The reference threads engine-managed stateful PRNG resources into each
kernel; the TPU-native design is stateless `jax.random` with threaded
keys — every op takes an injected ``_rng`` key split from the global
seed state (see random_state.py), which is what makes sampling
reproducible *and* jit/pmap-safe.
"""
import jax
import jax.numpy as jnp

from .registry import defop


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def _dt(dtype):
    from ..base import np_dtype
    return np_dtype(dtype if dtype not in (None, "None") else "float32")


@defop("_random_uniform", aliases=["uniform", "random_uniform"],
       needs_rng=True, differentiable=False)
def random_uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None,
                   _rng=None):
    return jax.random.uniform(_rng, _shape(shape), _dt(dtype), low, high)


@defop("_random_normal", aliases=["normal", "random_normal"],
       needs_rng=True, differentiable=False)
def random_normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None,
                  _rng=None):
    return loc + scale * jax.random.normal(_rng, _shape(shape), _dt(dtype))


@defop("_random_gamma", aliases=["random_gamma"], needs_rng=True,
       differentiable=False)
def random_gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None,
                 _rng=None):
    return beta * jax.random.gamma(_rng, alpha, _shape(shape), _dt(dtype))


@defop("_random_exponential", aliases=["random_exponential"],
       needs_rng=True, differentiable=False)
def random_exponential(lam=1.0, shape=(), dtype="float32", ctx=None,
                       _rng=None):
    return jax.random.exponential(_rng, _shape(shape), _dt(dtype)) / lam


@defop("_random_poisson", aliases=["random_poisson"], needs_rng=True,
       differentiable=False)
def random_poisson(lam=1.0, shape=(), dtype="float32", ctx=None, _rng=None):
    return jax.random.poisson(_rng, lam, _shape(shape)).astype(_dt(dtype))


@defop("_random_negative_binomial", aliases=["random_negative_binomial"],
       needs_rng=True, differentiable=False)
def random_negative_binomial(k=1, p=1.0, shape=(), dtype="float32",
                             ctx=None, _rng=None):
    k1, k2 = jax.random.split(_rng)
    lam = jax.random.gamma(k1, float(k), _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(_dt(dtype))


@defop("_random_generalized_negative_binomial",
       aliases=["random_generalized_negative_binomial"], needs_rng=True,
       differentiable=False)
def random_gen_neg_binomial(mu=1.0, alpha=1.0, shape=(), dtype="float32",
                            ctx=None, _rng=None):
    k1, k2 = jax.random.split(_rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(_dt(dtype))


# tensor-parameter multisample variants (ref: multisample_op.cc)
@defop("_sample_uniform", needs_rng=True, differentiable=False)
def sample_uniform(low, high, shape=(), dtype="float32", _rng=None):
    s = low.shape + _shape(shape)
    u = jax.random.uniform(_rng, s, _dt(dtype))
    return (low.reshape(low.shape + (1,) * len(_shape(shape)))
            + u * (high - low).reshape(
                low.shape + (1,) * len(_shape(shape))))


@defop("_sample_normal", needs_rng=True, differentiable=False)
def sample_normal(mu, sigma, shape=(), dtype="float32", _rng=None):
    s = mu.shape + _shape(shape)
    ext = (1,) * len(_shape(shape))
    z = jax.random.normal(_rng, s, _dt(dtype))
    return mu.reshape(mu.shape + ext) + z * sigma.reshape(sigma.shape + ext)


@defop("_sample_gamma", needs_rng=True, differentiable=False)
def sample_gamma(alpha, beta, shape=(), dtype="float32", _rng=None):
    s = alpha.shape + _shape(shape)
    ext = (1,) * len(_shape(shape))
    g = jax.random.gamma(_rng, alpha.reshape(alpha.shape + ext), s,
                         _dt(dtype))
    return g * beta.reshape(beta.shape + ext)


@defop("_sample_exponential", needs_rng=True, differentiable=False)
def sample_exponential(lam, shape=(), dtype="float32", _rng=None):
    s = lam.shape + _shape(shape)
    ext = (1,) * len(_shape(shape))
    return (jax.random.exponential(_rng, s, _dt(dtype))
            / lam.reshape(lam.shape + ext))


@defop("_sample_poisson", needs_rng=True, differentiable=False)
def sample_poisson(lam, shape=(), dtype="float32", _rng=None):
    s = lam.shape + _shape(shape)
    ext = (1,) * len(_shape(shape))
    return jax.random.poisson(
        _rng, lam.reshape(lam.shape + ext), s).astype(_dt(dtype))


@defop("_sample_multinomial", aliases=["sample_multinomial"],
       needs_rng=True, differentiable=False,
       num_outputs=lambda p: 2 if p.get("get_prob") else 1)
def sample_multinomial(data, shape=(), get_prob=False, dtype="int32",
                       _rng=None):
    """Draw class indices from probability rows (ref:
    sample_multinomial_op.cc)."""
    n = _shape(shape)
    count = 1
    for s in n:
        count *= s
    logits = jnp.log(jnp.maximum(data, 1e-30))
    idx = jax.random.categorical(
        _rng, logits[..., None, :].repeat(max(count, 1), axis=-2), axis=-1)
    out_shape = data.shape[:-1] + n
    idx = idx.reshape(out_shape).astype(_dt(dtype))
    if get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1)
            .reshape(data.shape[:-1] + (1,) * max(len(n), 1)
                     + (data.shape[-1],)).astype(jnp.float32),
            idx[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
        return idx, logp
    return idx


@defop("_shuffle", aliases=["shuffle"], needs_rng=True,
       differentiable=False)
def shuffle(data, _rng=None):
    return jax.random.permutation(_rng, data, axis=0)


@defop("_random_randint", needs_rng=True, differentiable=False)
def random_randint(low=0, high=1, shape=(), dtype="int32", ctx=None,
                   _rng=None):
    """Uniform integers in [low, high) via jax.random.randint (exact
    endpoint distribution; no float truncation bias)."""
    return jax.random.randint(_rng, _shape(shape), int(low), int(high)
                              ).astype(_dt(dtype))
