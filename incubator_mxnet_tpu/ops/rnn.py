"""Fused RNN op: multi-layer (bi)directional RNN/LSTM/GRU via lax.scan.

Role analog of the reference's `RNN` op (ref: src/operator/rnn-inl.h,
registered rnn.cc; GPU-only via cuDNN `cudnn_rnn-inl.h` — the CPU path
was never implemented, rnn-inl.h:319 LOG(FATAL)).  This TPU-native
version works everywhere: per-timestep input projections are hoisted
out of the scan into one big (T*N, C) x (C, G*H) matmul that tiles
onto the MXU; only the (N,H) x (H,G*H) recurrent matmul stays inside
`lax.scan`.

API parity with the reference op:
  RNN(data, parameters, state[, state_cell], state_size=, num_layers=,
      mode='rnn_relu'|'rnn_tanh'|'lstm'|'gru', bidirectional=False,
      p=0.0, state_outputs=False)
  data (T, N, C) time-major; parameters is the flat packed vector in
  cuDNN order (all gate weights layer-major then all gate biases —
  the packing gluon's rnn_layer produces); state (L*D, N, H).
Gate order: LSTM i,f,g,o; GRU r,z,n (cuDNN convention, what the
reference's fused kernels used).
"""
import jax
import jax.numpy as jnp

from .registry import defop

__all__ = ["rnn"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _unpack_params(flat, mode, num_layers, input_size, H, bidir):
    """Walk the flat cuDNN-packed vector into per-(layer,dir) W/b."""
    G = _GATES[mode]
    D = 2 if bidir else 1
    weights, biases = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * D
        for _ in range(D):
            w_ih = flat[off:off + G * H * in_sz].reshape(G * H, in_sz)
            off += G * H * in_sz
            w_hh = flat[off:off + G * H * H].reshape(G * H, H)
            off += G * H * H
            weights.append((w_ih, w_hh))
    for layer in range(num_layers):
        for _ in range(D):
            b_ih = flat[off:off + G * H]
            off += G * H
            b_hh = flat[off:off + G * H]
            off += G * H
            biases.append((b_ih, b_hh))
    return weights, biases


def rnn_param_size(mode, num_layers, input_size, state_size,
                   bidirectional=False):
    """Length of the flat parameter vector (helper for frontends)."""
    G = _GATES[mode]
    D = 2 if bidirectional else 1
    H = state_size
    n = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * D
        n += D * (G * H * in_sz + G * H * H + 2 * G * H)
    return n


def _cell_step(mode, H):
    """Vanilla-RNN step (lstm/gru have bespoke steps in _run_layer)."""
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

    def step(carry, g):
        (h,) = carry
        h_new = act(g)
        return (h_new,), h_new
    return step


def _run_layer(x, w_ih, w_hh, b_ih, b_hh, h0, c0, mode, reverse,
               clip=None):
    """One direction of one layer. x (T,N,C) -> y (T,N,H), finals."""
    if reverse:
        x = jnp.flip(x, 0)
    H = h0.shape[-1]
    xg = jnp.einsum("tnc,gc->tng", x, w_ih) + b_ih  # hoisted matmul

    if mode == "gru":
        def step(carry, xg_t):
            (h,) = carry
            hg = h @ w_hh.T + b_hh
            xr, xz, xn = jnp.split(xg_t, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1 - z) * n + z * h
            return (h_new,), h_new
        (hT,), ys = jax.lax.scan(step, (h0,), xg)
        finals = (hT,)
    elif mode == "lstm":
        def step(carry, xg_t):
            h, c = carry
            g = xg_t + h @ w_hh.T + b_hh
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + \
                jax.nn.sigmoid(i) * jnp.tanh(gg)
            if clip is not None:
                # per-timestep cell-state clip BEFORE the output gate,
                # cuDNN parity (ref: rnn-inl.h lstm_state_clip_{min,max})
                c_new = jnp.clip(c_new, clip[0], clip[1])
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        (hT, cT), ys = jax.lax.scan(step, (h0, c0), xg)
        finals = (hT, cT)
    else:
        cell = _cell_step(mode, H)

        def step(carry, xg_t):
            (h,) = carry
            g = xg_t + h @ w_hh.T + b_hh
            return cell((h,), g)
        (hT,), ys = jax.lax.scan(step, (h0,), xg)
        finals = (hT,)
    if reverse:
        ys = jnp.flip(ys, 0)
    return ys, finals


def _rnn_num_outputs(params):
    return 3 if params.get("state_outputs", False) and \
        params.get("mode", "lstm") == "lstm" else \
        (2 if params.get("state_outputs", False) else 1)


@defop("RNN", variadic=True, needs_rng=True, needs_mode=True,
       cache_vjp=True,
       num_outputs=_rnn_num_outputs)
def rnn(*args, state_size=0, num_layers=1, mode="lstm",
        bidirectional=False, p=0.0, state_outputs=False,
        lstm_state_clip_min=None, lstm_state_clip_max=None,
        _rng=None, _training=False):
    """Fused multi-layer RNN (ref: src/operator/rnn-inl.h RNNParam)."""
    data, flat = args[0], args[1]
    state = args[2]
    if mode == "lstm" and len(args) < 4:
        raise ValueError(
            "RNN(mode='lstm') requires a state_cell input "
            "(data, parameters, state, state_cell)")
    state_cell = args[3] if mode == "lstm" and len(args) > 3 else None
    T, N, C = data.shape
    H = int(state_size)
    L = int(num_layers)
    D = 2 if bidirectional else 1
    weights, biases = _unpack_params(flat, mode, L, C, H, bidirectional)

    clip = (lstm_state_clip_min, lstm_state_clip_max) \
        if lstm_state_clip_min is not None else None
    x = data
    h_finals, c_finals = [], []
    for layer in range(L):
        outs = []
        for d in range(D):
            idx = layer * D + d
            w_ih, w_hh = weights[idx]
            b_ih, b_hh = biases[idx]
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None else None
            ys, finals = _run_layer(x, w_ih, w_hh, b_ih, b_hh, h0, c0,
                                    mode, reverse=(d == 1), clip=clip)
            outs.append(ys)
            h_finals.append(finals[0])
            if mode == "lstm":
                c_finals.append(finals[1])
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and _training and layer < L - 1:
            keep = 1.0 - p
            sub = jax.random.fold_in(_rng, layer)
            mask = jax.random.bernoulli(sub, keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    if not state_outputs:
        return x
    h_out = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        return x, h_out, jnp.stack(c_finals, axis=0)
    return x, h_out
