"""Shape-manipulation and linear-algebra ops (ref:
src/operator/tensor/matrix_op.cc, dot.cc, concat.cc, slice_channel.cc,
swapaxis.cc, pad.cc, crop.cc, control_flow_op.cc, init_op.cc cast).

On TPU, `dot`/`batch_dot` are the MXU ops; everything else is layout
work that XLA folds into surrounding fusions.
"""
import numpy as np

import jax.numpy as jnp

from .registry import defop


# ------------------------------------------------------------------ reshape
@defop("Reshape", aliases=["reshape"])
def reshape(data, shape=(), reverse=False):
    """Reshape with the reference's special codes 0, -1, -2, -3, -4
    (ref: matrix_op-inl.h ReshapeParam)."""
    src = list(data.shape)
    if reverse:
        src = src[::-1]
        shape = tuple(shape)[::-1]
    out, i = [], 0
    it = iter(range(len(shape)))
    shape = list(shape)
    k = 0
    while k < len(shape):
        s = shape[k]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            d1, d2 = shape[k + 1], shape[k + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; k += 2
        else:
            out.append(int(s)); i += 1
        k += 1
    if reverse:
        out = out[::-1]
    if -1 in out:
        # resolve the wildcard ourselves: jax's -1 inference divides
        # by the product of the other dims, which is 0 for zero-size
        # arrays (found by the degenerate-shape sweep)
        known = 1
        for d in out:
            if d != -1:
                known *= int(d)
        total = int(np.prod(data.shape))
        out[out.index(-1)] = total // known if known > 0 else 0
    return data.reshape(tuple(out))


@defop("Flatten", aliases=["flatten"])
def flatten(data):
    """Collapse all dims but the first (ref: matrix_op.cc Flatten).
    The trailing size is computed explicitly so zero-size leading
    dims do not trip -1 inference."""
    rest = 1
    for d in data.shape[1:]:
        rest *= int(d)
    return data.reshape((data.shape[0], rest))


@defop("transpose")
def transpose(data, axes=()):
    ax = tuple(axes) if axes else None
    return jnp.transpose(data, ax)


@defop("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, int(axis))


@defop("SwapAxis", aliases=["swapaxes"])
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, int(dim1), int(dim2))


@defop("squeeze")
def squeeze(data, axis=None):
    ax = None if axis is None else (
        (int(axis),) if isinstance(axis, int) else tuple(axis))
    return jnp.squeeze(data, ax)


# ------------------------------------------------------------------ slicing
def _slice_tuple(begin, end, step, ndim, shape):
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = (list(step) + [None] * (ndim - len(step))) if step else [None] * ndim
    sl = []
    for b, e, s in zip(begin, end, step):
        sl.append(slice(b, e, s))
    return tuple(sl)


@defop("slice", aliases=["crop"])
def slice_op(data, begin=(), end=(), step=()):
    """Python-slicing semantics slice (ref: matrix_op.cc slice)."""
    return data[_slice_tuple(begin, end, step, data.ndim, data.shape)]


@defop("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    axis = int(axis) % data.ndim
    sl = [slice(None)] * data.ndim
    sl[axis] = slice(begin, end)
    return data[tuple(sl)]


@defop("slice_like")
def slice_like(data, shape_like, axes=()):
    axes_ = tuple(axes) if axes else tuple(range(shape_like.ndim))
    sl = [slice(None)] * data.ndim
    for a in axes_:
        sl[a % data.ndim] = slice(0, shape_like.shape[a % shape_like.ndim])
    return data[tuple(sl)]


@defop("_slice_assign", aliases=["_crop_assign"])
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    return lhs.at[_slice_tuple(begin, end, step, lhs.ndim, lhs.shape)].set(rhs)


@defop("_slice_assign_scalar", aliases=["_crop_assign_scalar"])
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    sl = _slice_tuple(begin, end, step, data.ndim, data.shape)
    return data.at[sl].set(jnp.asarray(scalar, data.dtype))


@defop("clip")
def clip(data, a_min=0.0, a_max=1.0):
    return jnp.clip(data, a_min, a_max)


@defop("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, int(repeats),
                      axis=None if axis is None else int(axis))


@defop("tile")
def tile(data, reps=()):
    return jnp.tile(data, tuple(reps))


@defop("reverse", aliases=["flip"])
def reverse(data, axis=()):
    ax = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, ax)


# ------------------------------------------------------------- concat/split
@defop("Concat", aliases=["concat"], variadic=True)
def concat(*args, dim=1, num_args=None):
    """Concatenate along ``dim`` (ref: src/operator/concat.cc)."""
    return jnp.concatenate(args, axis=int(dim))


@defop("stack", variadic=True)
def stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=int(axis))


def _split_outputs(params):
    return int(params.get("num_outputs", 1))


@defop("SliceChannel", aliases=["split"], num_outputs=_split_outputs)
def slice_channel(data, num_outputs=1, axis=1, squeeze_axis=False):
    """Split into equal parts (ref: slice_channel.cc)."""
    parts = jnp.split(data, int(num_outputs), axis=int(axis))
    if squeeze_axis:
        parts = [jnp.squeeze(p, int(axis)) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


# ------------------------------------------------------------------ matmul
@defop("dot", aliases=["_sparse_dot"])
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Matrix product on the MXU (ref: src/operator/tensor/dot.cc).

    For >2-D inputs follows the reference: reshape lhs to
    (prod(head), last) and rhs to (first, prod(tail)).
    """
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    a2 = a.reshape((-1, a.shape[-1]))
    b2 = b.reshape((b.shape[0], -1))
    out = jnp.dot(a2, b2, preferred_element_type=jnp.result_type(a2))
    return out.reshape(a.shape[:-1] + b.shape[1:])


@defop("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Batched matmul (ref: dot.cc batch_dot)."""
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


# ------------------------------------------------------------------ pad
@defop("Pad", aliases=["pad"])
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    """Pad NCHW/NCDHW (ref: src/operator/pad.cc). pad_width is the
    flat (before, after) per-axis list like the reference."""
    pw = list(pad_width)
    pairs = [(int(pw[2 * i]), int(pw[2 * i + 1]))
             for i in range(len(pw) // 2)]
    while len(pairs) < data.ndim:
        pairs.append((0, 0))
    if mode == "constant":
        return jnp.pad(data, pairs, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pairs, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pairs, mode="reflect")
    raise ValueError(f"unknown pad mode {mode}")


# ------------------------------------------------------------------ where
@defop("where")
def where(condition, x, y):
    """Elementwise select (ref: control_flow_op.cc where)."""
    if condition.ndim == 1 and x.ndim > 1:
        condition = condition.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(condition != 0, x, y)


# ------------------------------------------------------------------ casts
@defop("Cast", aliases=["cast"])
def cast(data, dtype="float32"):
    from ..base import np_dtype
    return data.astype(np_dtype(dtype))


@defop("amp_cast")
def amp_cast(data, dtype="float16"):
    from ..base import np_dtype
    return data.astype(np_dtype(dtype))


@defop("zeros_like", aliases=["_sparse_zeros_like"])
def zeros_like(data):
    return jnp.zeros_like(data)


@defop("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@defop("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    return lhs + 0


@defop("_CrossDeviceCopy", aliases=["_cross_device_copy"])
def cross_device_copy(data):
    """Explicit device boundary marker (ref: cross_device_copy.cc).
    Under jit this is an identity; placement is handled by sharding
    annotations instead of graph-inserted copy nodes."""
    return data + 0


@defop("einsum", variadic=True, aliases=["_npi_einsum"])
def einsum(*operands, subscripts=""):
    """Einstein summation over any number of operands (the np.einsum
    surface MXNet 1.6+ exposes as mx.np.einsum; ref:
    src/operator/numpy/np_einsum_op.cc).  Lowers to jnp.einsum —
    contractions land on the MXU."""
    if not subscripts:
        raise ValueError("einsum needs subscripts=")
    return jnp.einsum(subscripts, *operands)


@defop("cumsum", aliases=["_np_cumsum"])
def cumsum(data, axis=None, dtype=None):
    """Cumulative sum (ref: src/operator/numpy/np_cumsum.cc).

    ``dtype`` is the ACCUMULATOR type (numpy semantics): int8 data
    with dtype='int32' accumulates in int32 — no wraparound before
    the cast."""
    from ..base import np_dtype
    return jnp.cumsum(data, axis=axis,
                      dtype=np_dtype(dtype) if dtype else None)


def rope_fn(data, base=10000.0, offset=0):
    """Rotary position embedding (RoFormer; a positional scheme the
    reference predates but LM users expect).  data: (B_, L, D) or
    (B, L, H, D) — positions run along axis 1 either way.  Rotates
    feature pairs (d, d + D/2) by position-dependent angles; applied
    to q and k, attention scores become functions of RELATIVE
    position.  ``offset`` shifts the absolute positions (may be a
    traced scalar — the KV-cache decode path passes the step index).
    """
    l, d = data.shape[1], data.shape[-1]
    if d % 2:
        raise ValueError(
            f"rope needs an even feature dim (got {d}): it rotates "
            "pairs (i, i + D/2) — pick d_model/n_heads even")
    half = d // 2
    pos = jnp.arange(l, dtype=jnp.float32) + offset
    inv = float(base) ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos[:, None] * inv[None, :]              # (L, D/2)
    shape = (1, l) + (1,) * (data.ndim - 3) + (half,)
    cos = jnp.cos(ang).reshape(shape)
    sin = jnp.sin(ang).reshape(shape)
    x1, x2 = data[..., :half], data[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(data.dtype)


@defop("_rope", arg_names=["data"])
def rope(data, base=10000.0, offset=0):
    """Registry surface for :func:`rope_fn` (docstring above)."""
    return rope_fn(data, base=float(base), offset=float(offset))
