"""Central operator registry — the single source of truth for the op
surface.

Role analog of the reference's NNVM op registry (ref:
include/mxnet/op_attr_types.h FCompute registration, and
python/mxnet/ndarray/register.py which code-generates the Python op
surface from the C registry).  Every op is declared exactly once here
with a pure-JAX compute function; the ``nd`` (imperative), ``sym``
(symbolic) and gluon surfaces are generated from these entries, so the
three frontends can never drift apart.

An OpDef's ``fn`` maps jnp arrays + static Python params -> jnp
array(s).  Because fns are pure and jit-friendly (no data-dependent
Python control flow), a whole graph of them lowers to a single XLA
executable — the TPU answer to the reference's per-node engine pushes.
"""
import inspect

__all__ = ["OpDef", "defop", "alias", "get_op", "find_op", "list_ops",
           "OPS"]

OPS = {}


class OpDef:
    """A registered operator.

    Attributes
    ----------
    name : canonical op name (reference-compatible, e.g. 'broadcast_add')
    fn : compute function ``fn(*inputs, **params) -> out | tuple``
    num_outputs : int or callable(params)->int
    variadic : True if the op takes a variable number of tensor inputs
    needs_mode : fn takes a ``_training`` kwarg (dropout, BN, ...)
    needs_rng : fn takes a ``_rng`` kwarg (jax.random key)
    num_aux : number of trailing inputs that are auxiliary states
        (mutated in-place by the frontend, e.g. BatchNorm moving stats);
        when >0 in training mode fn returns extra outputs with their
        updated values appended after the regular outputs.
    arg_names : names of tensor inputs (for symbol list_arguments)
    differentiable : participate in autograd via jax.vjp
    """

    __slots__ = ("name", "fn", "num_outputs", "variadic", "needs_mode",
                 "needs_rng", "num_aux", "arg_names", "aux_names",
                 "differentiable", "param_defaults", "doc",
                 "cache_vjp")

    def __init__(self, name, fn, num_outputs=1, variadic=False,
                 needs_mode=False, needs_rng=False, num_aux=0,
                 arg_names=None, aux_names=None, differentiable=True,
                 cache_vjp=False):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.variadic = variadic
        self.needs_mode = needs_mode
        self.needs_rng = needs_rng
        self.num_aux = num_aux
        self.aux_names = aux_names or []
        self.differentiable = differentiable
        # Ops whose fn binds composite control-flow primitives
        # (lax.scan / while) must dispatch through a STABLE cached
        # jit pair in eager mode: the generic per-call jax.vjp on a
        # fresh closure re-traces a fresh jaxpr, and scan's compile
        # cache keys on jaxpr identity — so every eager step paid a
        # full XLA compile (and LLVM eventually exhausted memory on
        # long loops).  Per-primitive eager caches cover everything
        # else, so this stays opt-in.
        self.cache_vjp = cache_vjp
        self.doc = fn.__doc__ or ""
        if arg_names is None and not variadic:
            sig = inspect.signature(fn)
            arg_names = [p.name for p in sig.parameters.values()
                         if p.kind in (p.POSITIONAL_ONLY,
                                       p.POSITIONAL_OR_KEYWORD)
                         and p.default is p.empty
                         and not p.name.startswith("_")]
        self.arg_names = arg_names or []
        # static param defaults (kwargs of fn)
        sig = inspect.signature(fn)
        self.param_defaults = {
            p.name: p.default for p in sig.parameters.values()
            if p.default is not p.empty and not p.name.startswith("_")}

    def n_outputs(self, params):
        if callable(self.num_outputs):
            return self.num_outputs(params)
        return self.num_outputs

    def __repr__(self):
        return f"OpDef({self.name})"


def defop(name, aliases=(), **attrs):
    """Decorator: register the function as op ``name``."""
    def _reg(fn):
        op = OpDef(name, fn, **attrs)
        if name in OPS:
            raise ValueError(f"op '{name}' registered twice")
        OPS[name] = op
        for a in aliases:
            if a in OPS:
                raise ValueError(f"op alias '{a}' registered twice")
            OPS[a] = op
        return fn
    return _reg


def alias(existing, *new_names):
    """Register additional Python-facing names for an existing op
    (analog of nnvm ``add_alias``, ref: SURVEY.md Appendix A)."""
    op = OPS[existing]
    for n in new_names:
        if n in OPS and OPS[n] is not op:
            raise ValueError(f"alias '{n}' conflicts")
        OPS[n] = op


def get_op(name):
    try:
        return OPS[name]
    except KeyError:
        raise KeyError(f"unknown operator '{name}'") from None


def find_op(name):
    return OPS.get(name)


def list_ops():
    return sorted(OPS)
