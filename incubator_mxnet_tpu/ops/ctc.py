"""CTC loss, TPU-first (ref: src/operator/contrib/ctc_loss.cc, which
wraps warp-ctc; same conventions, different machinery).

The reference runs warp-ctc's hand-written alpha/beta kernels; here the
log-semiring alpha recursion is a `lax.scan` over time with masking for
variable data/label lengths, and the exact gradient (softmax minus
alignment posterior) comes out of `jax.grad` through the scan — no
hand-written backward needed.

Conventions (ref docstring ctc_loss.cc:72-105):
- data (T, B, C) unnormalized activations; softmax applied internally
- label (B, L) int; blank channel 0 when blank_label='first' (padding
  value 0), channel C-1 when 'last' (padding value -1)
- optional data_lengths (B,) / label_lengths (B,) inputs gated by
  use_data_lengths / use_label_lengths
- out (B,) positive costs -log p(label | data)
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import defop

NEG = -1e30  # -inf substitute that keeps logaddexp gradients finite


def _logaddexp(a, b):
    # The dead branch must be NaN-free even in its GRADIENT: with
    # a = b = NEG the untaken branch's vjp is exp(a-m)/(exp(a-m)
    # + exp(b-m)) = 0/0, and where-grad's 0 * NaN poisons the whole
    # backward (autograd tape -> adam -> weights).  Clamp the inputs
    # of the dead branch too, not just the max (double-where trick).
    ok = jnp.maximum(a, b) > NEG / 2
    a_safe = jnp.where(ok, a, 0.0)
    b_safe = jnp.where(ok, b, 0.0)
    m_safe = jnp.maximum(a_safe, b_safe)
    out = m_safe + jnp.log(jnp.exp(a_safe - m_safe)
                           + jnp.exp(b_safe - m_safe))
    return jnp.where(ok, out, NEG)


def _ctc_single(log_probs, labels, T_len, L_len, blank):
    """One sequence: log_probs (T, C), labels (L,) already 0-indexed
    w.r.t. the data channels, lengths as scalars."""
    T, C = log_probs.shape
    L = labels.shape[0]
    S = 2 * L + 1

    s_idx = jnp.arange(S)
    z = jnp.where(s_idx % 2 == 0, blank,
                  labels[jnp.clip((s_idx - 1) // 2, 0, L - 1)])
    # s is inside the extended sequence for this label length
    s_valid = s_idx < 2 * L_len + 1
    # skip-transition allowed: odd position, differs from label 2 back
    z_m2 = jnp.where(s_idx >= 2, z[jnp.clip(s_idx - 2, 0, S - 1)], -1)
    allow_skip = (z != blank) & (z != z_m2)

    lp_z = log_probs[:, jnp.clip(z, 0, C - 1)]      # (T, S)

    alpha0 = jnp.full((S,), NEG)
    alpha0 = alpha0.at[0].set(lp_z[0, 0])
    alpha0 = alpha0.at[1].set(jnp.where(L_len > 0, lp_z[0, 1], NEG))

    def step(alpha, xs):
        lp_t, t = xs
        prev1 = jnp.concatenate([jnp.full((1,), NEG), alpha[:-1]])
        prev2 = jnp.concatenate([jnp.full((2,), NEG), alpha[:-2]])
        acc = _logaddexp(alpha, prev1)
        acc = jnp.where(allow_skip, _logaddexp(acc, prev2), acc)
        new = jnp.where(s_valid, acc + lp_t, NEG)
        # freeze past the true sequence length
        new = jnp.where(t < T_len, new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0,
                        (lp_z[1:], jnp.arange(1, T)))
    end = 2 * L_len                                  # index of last blank
    a_last = alpha[jnp.clip(end, 0, S - 1)]
    a_prev = jnp.where(L_len > 0,
                       alpha[jnp.clip(end - 1, 0, S - 1)], NEG)
    return -_logaddexp(a_last, a_prev)


@defop("ctc_loss", aliases=("_contrib_CTCLoss", "CTCLoss",
                            "_contrib_ctc_loss"), variadic=True,
       cache_vjp=True)
def ctc_loss(*inputs, use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """CTC loss (ref: src/operator/contrib/ctc_loss.cc).
    inputs: data (T, B, C), label (B, L)
    [, data_lengths (B,)][, label_lengths (B,)] -> costs (B,)."""
    data, label = inputs[0], inputs[1]
    k = 2
    data_lengths = label_lengths = None
    if use_data_lengths:
        data_lengths = inputs[k]
        k += 1
    if use_label_lengths:
        label_lengths = inputs[k]

    T, B, C = data.shape
    lab = label.astype(jnp.int32)
    first = (blank_label == "first")
    blank = 0 if first else C - 1
    pad = 0 if first else -1

    if label_lengths is None:
        lab_len = (lab != pad).astype(jnp.int32).sum(axis=1)
    else:
        lab_len = label_lengths.astype(jnp.int32)
    if data_lengths is None:
        dat_len = jnp.full((B,), T, jnp.int32)
    else:
        dat_len = data_lengths.astype(jnp.int32)

    # channel indices of the labels: with blank 'first' the data
    # channels for real labels are already 1..C-1 as passed
    log_probs = jax.nn.log_softmax(data.astype(jnp.float32), axis=2)

    costs = jax.vmap(
        lambda lp, lb, tl, ll: _ctc_single(lp, lb, tl, ll, blank),
        in_axes=(1, 0, 0, 0))(log_probs, lab, dat_len, lab_len)
    return costs.astype(data.dtype)
