"""Mixture-of-Experts FFN with top-2 routing (GShard/Switch style).

A capability the reference predates, designed TPU-first the way the
SURVEY (§5 long-context/parallelism) prescribes for new scale-out
features: routing is *dense dispatch* — fixed-capacity one-hot
dispatch/combine tensors contracted with einsums — so every shape is
static under jit, the expert matmuls are batched over the expert
dimension (one big MXU contraction, not E small ones), and sharding
the expert dimension over the mesh's 'ep' axis makes GSPMD insert the
token all-to-alls automatically (the expert-parallel pattern of
GShard; see parallel/sharding.py's ep rules).

Registered as the differentiable 2-output op ``_moe_ffn`` so the
eager tape, hybridized blocks, and ShardedTrainStep all route/
backprop through identical code: outputs are (tokens_out, aux_loss)
where aux_loss is the load-balance penalty (E * sum_e f_e * P_e;
f_e = top-1 dispatch fraction, P_e = mean router probability) the
training loss should add with a small weight (~1e-2).

Tokens over capacity (C = ceil(cf * 2 * T / E) per expert) are
DROPPED — their expert contribution is zero and the residual stream
carries them, the standard GShard overflow semantic that keeps shapes
static.
"""
import math

import jax
import jax.numpy as jnp

from .registry import defop

__all__ = ["moe_ffn_fn", "top2_gating"]


def top2_gating(logits, capacity, renorm=True):
    """GShard top-2 gating with fixed expert capacity.

    logits : (T, E) router scores (any float dtype; gating runs fp32)
    returns (combine (T, E, C) f32, dispatch (T, E, C) f32 0/1,
             aux_loss scalar f32)
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    idx1 = jnp.argmax(probs, axis=-1)                   # (T,)
    mask1 = jax.nn.one_hot(idx1, e, dtype=jnp.float32)  # (T, E)
    p1 = jnp.sum(probs * mask1, axis=-1)
    probs_wo1 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=jnp.float32)
    p2 = jnp.sum(probs * mask2, axis=-1)

    if renorm:
        denom = p1 + p2 + 1e-9
        g1, g2 = p1 / denom, p2 / denom
    else:
        g1, g2 = p1, p2

    # position of each token in its expert's buffer; second choices
    # queue behind ALL first choices (GShard's ordering)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1       # (T, E)
    count1 = jnp.sum(mask1, axis=0, keepdims=True)          # (1, E)
    pos2 = (jnp.cumsum(mask2, axis=0) - 1.0 + count1) * mask2

    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    oh1 = jax.nn.one_hot(pos1.astype(jnp.int32), capacity,
                         dtype=jnp.float32) * keep1[..., None]
    oh2 = jax.nn.one_hot(pos2.astype(jnp.int32), capacity,
                         dtype=jnp.float32) * keep2[..., None]
    dispatch = oh1 + oh2                                    # (T, E, C)
    combine = g1[:, None, None] * oh1 + g2[:, None, None] * oh2

    # load-balance aux: E * sum_e (top1 dispatch fraction * mean prob)
    f = jnp.mean(mask1, axis=0)
    p_mean = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(f * p_mean)
    return combine, dispatch, aux


def moe_ffn_fn(data, router_weight, up_weight, up_bias, down_weight,
               down_bias, capacity_factor=1.25, renorm=True):
    """Pure-jnp MoE FFN on flattened tokens.

    data          : (T, D)
    router_weight : (E, D)   — FullyConnected (out, in) convention
    up_weight     : (E, H, D);  up_bias (E, H)
    down_weight   : (E, D, H); down_bias (E, D)
    returns (out (T, D) in data.dtype, aux_loss scalar f32)
    """
    t, d = data.shape
    e = router_weight.shape[0]
    capacity = max(1, math.ceil(float(capacity_factor) * 2 * t / e))

    logits = jnp.dot(data.astype(jnp.float32),
                     router_weight.astype(jnp.float32).T)
    combine, dispatch, aux = top2_gating(logits, capacity,
                                         renorm=renorm)

    xf = data.astype(jnp.float32)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, xf)
    hmid = jax.nn.relu(
        jnp.einsum("ecd,ehd->ech", expert_in,
                   up_weight.astype(jnp.float32))
        + up_bias.astype(jnp.float32)[:, None, :])
    expert_out = jnp.einsum("ech,edh->ecd", hmid,
                            down_weight.astype(jnp.float32)) \
        + down_bias.astype(jnp.float32)[:, None, :]
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.astype(data.dtype), aux


@defop("_moe_ffn", num_outputs=2,
       arg_names=["data", "router_weight", "up_weight", "up_bias",
                  "down_weight", "down_bias"])
def _moe_ffn(data, router_weight, up_weight, up_bias, down_weight,
             down_bias, capacity_factor=1.25, renorm=True):
    """Registry surface for :func:`moe_ffn_fn` (docstring above)."""
    return moe_ffn_fn(data, router_weight, up_weight, up_bias,
                      down_weight, down_bias,
                      capacity_factor=float(capacity_factor),
                      renorm=bool(renorm))
