"""Neural-network layer ops.

TPU-native re-design of the reference's layer zoo (ref:
src/operator/fully_connected.cc, convolution.cc, pooling.cc,
batch_norm.cc, activation.cc, dropout.cc, softmax_output.cc,
lrn.cc, l2_normalization.cc, instance_norm.cc, upsampling.cc,
sequence_{mask,last,reverse}.cc, regression_output.cc, svm_output.cc).

Convs/matmuls emit `lax.conv_general_dilated` / `jnp.dot` — the MXU
path; the cuDNN bindings of the reference have no analog because XLA
*is* the kernel library.  Stateful layers (BatchNorm moving stats)
surface as `num_aux` ops whose updated aux values are returned
functionally and written back by the frontend — the jit-safe version
of the reference's in-place aux mutation.
"""
import functools

import jax
import jax.numpy as jnp

from .registry import defop

# ---------------------------------------------------------------------------
# dense / conv / pool
# ---------------------------------------------------------------------------


@defop("FullyConnected", arg_names=["data", "weight", "bias"])
def fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                    flatten=True):
    """y = x W^T + b (ref: src/operator/fully_connected.cc)."""
    x = data.reshape((data.shape[0], -1)) if flatten else data
    out = jnp.dot(x, weight.T, preferred_element_type=jnp.result_type(x))
    if bias is not None and not no_bias:
        out = out + bias
    return out


def _conv_specs(ndim_spatial):
    if ndim_spatial == 1:
        return ("NCW", "OIW", "NCW")
    if ndim_spatial == 2:
        return ("NCHW", "OIHW", "NCHW")
    return ("NCDHW", "OIDHW", "NCDHW")


def _tup(v, n, default):
    if v is None or v == ():
        return (default,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    return t if len(t) == n else t + (default,) * (n - len(t))


@defop("Convolution", aliases=["Convolution_v1"],
       arg_names=["data", "weight", "bias"])
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, workspace=1024,
                no_bias=False, cudnn_tune=None, cudnn_off=False,
                layout=None):
    """N-D convolution, NC(D)HW layout (ref: convolution.cc).

    Lowers to one `lax.conv_general_dilated` — the MXU systolic path.
    """
    nsp = data.ndim - 2
    stride = _tup(stride, nsp, 1)
    dilate = _tup(dilate, nsp, 1)
    pad = _tup(pad, nsp, 0)
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape, _conv_specs(nsp))
    out = jax.lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=int(num_group),
        preferred_element_type=jnp.result_type(data))
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nsp)
    return out


@defop("Deconvolution", arg_names=["data", "weight", "bias"])
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), target_shape=(), num_filter=0,
                  num_group=1, workspace=1024, no_bias=True,
                  cudnn_tune=None, cudnn_off=False, layout=None):
    """Transposed convolution (ref: deconvolution.cc).

    Implemented as input-dilated convolution with the spatially-flipped,
    IO-swapped kernel — the canonical XLA formulation.
    """
    nsp = data.ndim - 2
    stride = _tup(stride, nsp, 1)
    dilate = _tup(dilate, nsp, 1)
    pad = _tup(pad, nsp, 0)
    adj = _tup(adj, nsp, 0)
    k = weight.shape[2:]
    # weight layout is (in, out/group, *k) for deconv in the reference
    w = jnp.flip(weight, axis=tuple(range(2, weight.ndim)))
    g = int(num_group)
    if g > 1:
        # (g*in_pg, out_pg, *k) -> (g*out_pg, in_pg, *k)
        in_pg = w.shape[0] // g
        w = w.reshape((g, in_pg, w.shape[1]) + k)
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((g * w.shape[1], in_pg) + k)
    else:
        w = jnp.swapaxes(w, 0, 1)
    eff_k = [dilate[i] * (k[i] - 1) + 1 for i in range(nsp)]
    padding = [(eff_k[i] - 1 - pad[i], eff_k[i] - 1 - pad[i] + adj[i])
               for i in range(nsp)]
    dn = jax.lax.conv_dimension_numbers(
        data.shape, w.shape, _conv_specs(nsp))
    out = jax.lax.conv_general_dilated(
        data, w, window_strides=(1,) * nsp, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=g,
        preferred_element_type=jnp.result_type(data))
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nsp)
    return out


@defop("Pooling", aliases=["Pooling_v1"])
def pooling(data, kernel=(), pool_type="max", global_pool=False,
            stride=(), pad=(), pooling_convention="valid",
            cudnn_off=False):
    """Max/avg/sum pooling via reduce_window (ref: pooling.cc, nn/pool.h)."""
    nsp = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nsp
        pad = (0,) * nsp
    kernel = _tup(kernel, nsp, 1)
    stride = _tup(stride, nsp, 1)
    pad = _tup(pad, nsp, 0)
    padding = []
    for i in range(nsp):
        lo = hi = pad[i]
        if pooling_convention == "full":
            size = data.shape[2 + i] + 2 * pad[i] - kernel[i]
            rem = size % stride[i]
            if rem != 0:
                hi += stride[i] - rem
        padding.append((lo, hi))
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = [(0, 0), (0, 0)] + padding
    # NOTE: init values must be *Python scalars* so jax dispatches to
    # the differentiable reduce_window_{max,sum} primitives
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(data, init, jax.lax.max, window,
                                     strides, pads)
    zero = 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0
    summed = jax.lax.reduce_window(
        data, zero, jax.lax.add, window, strides, pads)
    if pool_type == "sum":
        return summed
    if pool_type == "avg":
        denom = 1
        for ki in kernel:
            denom *= ki
        return summed / jnp.asarray(denom, data.dtype)
    raise ValueError(f"unknown pool_type {pool_type}")


@defop("UpSampling", variadic=True)
def upsampling(*args, scale=1, sample_type="nearest", num_filter=0,
               multi_input_mode="concat", num_args=1, workspace=512):
    """Nearest/bilinear upsampling (ref: upsampling.cc)."""
    s = int(scale)
    outs = []
    for data in args[:1] if sample_type == "bilinear" else args:
        if sample_type == "nearest":
            out = jnp.repeat(jnp.repeat(data, s, axis=2), s, axis=3)
        else:
            n, c, h, w = data.shape
            out = jax.image.resize(data, (n, c, h * s, w * s), "bilinear")
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    return jnp.concatenate(outs, axis=1)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


def _stats_cast(x):
    """Normalization statistics accumulate in float32 for
    low-precision inputs; no-op at fp32 and above."""
    return x.astype(jnp.float32) \
        if x.dtype in (jnp.bfloat16, jnp.float16) else x


@defop("BatchNorm", aliases=["BatchNorm_v1", "CuDNNBatchNorm"],
       needs_mode=True, num_aux=2,
       arg_names=["data", "gamma", "beta"],
       aux_names=["moving_mean", "moving_var"])
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               _training=False):
    """Batch normalization (ref: src/operator/batch_norm.cc).

    Functional aux protocol: in training mode returns
    (out, new_moving_mean, new_moving_var); the frontend writes the
    updated stats back into the aux arrays (jit-safe replacement for
    the reference's in-place aux mutation).  Batch statistics
    accumulate in float32 for low-precision inputs (see layer_norm).
    """
    ax = int(axis) % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = [1] * data.ndim
    bshape[ax] = data.shape[ax]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if _training and not use_global_stats:
        # single-pass statistics: both channel reductions in ONE
        # sweep of the activation through HBM, vs jnp.var's
        # mean -> (x-mean)^2 second pass.  BN statistics are ~30% of
        # the ResNet-50 step device time (PERF.md
        # multiply_reduce_fusion row) and the workload is HBM-bound,
        # so halving the stat passes is the lever.  The sums are over
        # x - x0 with x0 one sample per channel (the textbook shifted
        # algorithm): E[(x-x0)^2] - E[x-x0]^2 is algebraically the
        # same variance but the raw E[x^2]-E[x]^2 form cancels
        # catastrophically when mean >> std.  No stop_gradient on
        # x0 — the shift cancels algebraically, so autodiff stays
        # exact.
        xs = _stats_cast(data)
        n = 1
        for i in red:
            n *= data.shape[i]
        idx = tuple(0 if i in red else slice(None)
                    for i in range(data.ndim))
        x0 = xs[idx]                               # (C,)
        xc = xs - x0.reshape(bshape)
        s1 = jnp.sum(xc, axis=red)
        s2 = jnp.sum(xc * xc, axis=red)
        mean = (x0 + s1 / n).astype(moving_mean.dtype)
        var = jnp.maximum(s2 / n - (s1 / n) ** 2, 0.0) \
            .astype(moving_var.dtype)
        new_mean = (momentum * moving_mean
                    + (1 - momentum) * jax.lax.stop_gradient(mean))
        new_var = (momentum * moving_var
                   + (1 - momentum) * jax.lax.stop_gradient(var))
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    # fused scale-shift form: fold gamma/rsqrt/mean/beta into per-
    # channel scale+shift vectors first, so the full-size pass is a
    # single fma instead of sub/mul/mul/add
    inv = jax.lax.rsqrt(var + eps)
    scale = (g * inv).astype(data.dtype)
    shift = (beta - mean * g * inv).astype(data.dtype)
    out = data * scale.reshape(bshape) + shift.reshape(bshape)
    out = out.astype(data.dtype)   # fp32 stats must not upcast the
    if _training:                  # activation stream
        return out, new_mean, new_var
    return out


@defop("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """Layer normalization over ``axis``.  Statistics accumulate in
    float32 for low-precision inputs (bf16's 8-bit mantissa loses the
    mean; the TPU recipe keeps stats fp32, XLA fuses the converts)."""
    ax = int(axis) % data.ndim
    x = _stats_cast(data)
    mean = jnp.mean(x, axis=ax, keepdims=True)
    var = jnp.var(x, axis=ax, keepdims=True)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    out = ((x - mean) * jax.lax.rsqrt(var + eps)
           * _stats_cast(gamma).reshape(shape)
           + _stats_cast(beta).reshape(shape))
    return out.astype(data.dtype)


@defop("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    """Instance norm over spatial dims (ref: instance_norm.cc);
    fp32 statistics for low-precision inputs (see layer_norm)."""
    red = tuple(range(2, data.ndim))
    x = _stats_cast(data)
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    out = ((x - mean) * jax.lax.rsqrt(var + eps)
           * _stats_cast(gamma).reshape(shape)
           + _stats_cast(beta).reshape(shape))
    return out.astype(data.dtype)


@defop("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    """(ref: l2_normalization.cc) modes instance/channel/spatial."""
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True)
                     + eps)
    elif mode == "channel":
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=1, keepdims=True)
                     + eps)
    elif mode == "spatial":
        red = tuple(range(2, data.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True)
                     + eps)
    else:
        raise ValueError(mode)
    return data / n


@defop("LRN")
def lrn(data, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    """Local response normalization across channels (ref: lrn.cc)."""
    n = int(nsize)
    sq = jnp.square(data)
    pad_lo, pad_hi = (n - 1) // 2, n // 2
    window = (1, n, 1, 1)
    acc = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add, window,
        (1, 1, 1, 1), [(0, 0), (pad_lo, pad_hi), (0, 0), (0, 0)])
    return data / jnp.power(knorm + alpha / n * acc, beta)


# ---------------------------------------------------------------------------
# activations / softmax
# ---------------------------------------------------------------------------


@defop("Activation")
def activation(data, act_type="relu"):
    """(ref: activation.cc) relu/sigmoid/tanh/softrelu/softsign."""
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise ValueError(f"unknown act_type {act_type}")


@defop("LeakyReLU", variadic=True, needs_rng=True, needs_mode=True)
def leaky_relu(*args, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334, _rng=None, _training=False):
    """(ref: leaky_relu.cc) leaky/prelu/elu/selu/rrelu."""
    data = args[0]
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        gamma = args[1].reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data > 0, data, gamma * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        scale, a = 1.0507009873554805, 1.6732632423543772
        return scale * jnp.where(data > 0, data, a * jnp.expm1(data))
    if act_type == "rrelu":
        if _training and _rng is not None:
            s = jax.random.uniform(_rng, data.shape, data.dtype,
                                   lower_bound, upper_bound)
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise ValueError(f"unknown act_type {act_type}")


@defop("softmax", aliases=["SoftmaxActivation"])
def softmax(data, axis=-1, temperature=None, mode="instance"):
    """(ref: nn/softmax.cc; SoftmaxActivation mode=channel -> axis=1)."""
    ax = 1 if mode == "channel" else int(axis)
    x = data / temperature if temperature else data
    return jax.nn.softmax(x, axis=ax)


@defop("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=int(axis))


@defop("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """Summed CE with integer labels (ref: loss_binary_op.cc)."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(
        logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


# ---------------------------------------------------------------------------
# output heads with implicit-loss gradients (custom VJP: the forward is
# identity/softmax but the backward is the loss gradient — exactly the
# reference's Output-op contract)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _softmax_output_fn(grad_scale, ignore_label, multi_output, use_ignore,
                       preserve_shape, normalization):
    @jax.custom_vjp
    def f(data, label):
        ax = 1 if (multi_output or preserve_shape) else -1
        return jax.nn.softmax(data, axis=ax)

    def fwd(data, label):
        out = f(data, label)
        return out, (out, label)

    def bwd(res, g):
        out, label = res
        ax = 1 if (multi_output or preserve_shape) else -1
        lbl = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(lbl, out.shape[ax], dtype=out.dtype,
                                axis=ax)
        grad = out - onehot
        if use_ignore:
            mask = (lbl != int(ignore_label)).astype(out.dtype)
            mask = jnp.expand_dims(mask, ax)
            grad = grad * mask
        scale = grad_scale
        if normalization == "batch":
            scale = scale / out.shape[0]
        elif normalization == "valid" and use_ignore:
            valid = jnp.maximum(
                jnp.sum((lbl != int(ignore_label)).astype(out.dtype)), 1.0)
            grad = grad / valid
        grad = grad * scale
        return grad, jnp.zeros_like(label)

    f.defvjp(fwd, bwd)
    return f


@defop("SoftmaxOutput", aliases=["Softmax"])
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False,
                   preserve_shape=False, normalization="null",
                   out_grad=False, smooth_alpha=0.0):
    """Softmax forward; cross-entropy gradient in backward
    (ref: src/operator/softmax_output.cc)."""
    f = _softmax_output_fn(float(grad_scale), float(ignore_label),
                           bool(multi_output), bool(use_ignore),
                           bool(preserve_shape), str(normalization))
    return f(data, label)


def _make_regression(name, grad_fn):
    @functools.lru_cache(maxsize=None)
    def _build(grad_scale, link):
        @jax.custom_vjp
        def f(data, label):
            return link(data)

        def fwd(data, label):
            out = f(data, label)
            return out, (out, label)

        def bwd(res, g):
            out, label = res
            num = 1
            for s in out.shape[1:]:
                num *= s
            grad = grad_fn(out, label.reshape(out.shape)) * (
                grad_scale / num)
            return grad, jnp.zeros_like(label)

        f.defvjp(fwd, bwd)
        return f

    def _op(data, label, grad_scale=1.0):
        return _build(float(grad_scale), _LINKS[name])(data, label)
    _op.__name__ = name
    _op.__doc__ = f"{name} (ref: regression_output.cc)."
    return _op


_LINKS = {
    "LinearRegressionOutput": lambda x: x,
    "MAERegressionOutput": lambda x: x,
    "LogisticRegressionOutput": jax.nn.sigmoid,
}

defop("LinearRegressionOutput")(_make_regression(
    "LinearRegressionOutput", lambda o, l: o - l))
defop("MAERegressionOutput")(_make_regression(
    "MAERegressionOutput", lambda o, l: jnp.sign(o - l)))
defop("LogisticRegressionOutput")(_make_regression(
    "LogisticRegressionOutput", lambda o, l: o - l))


@functools.lru_cache(maxsize=None)
def _svm_output_fn(margin, reg, use_linear):
    @jax.custom_vjp
    def f(d, l):
        return d * 1.0

    def fwd(d, l):
        return f(d, l), (d, l)

    def bwd(res, g):
        d, l = res
        lbl = l.astype(jnp.int32)
        onehot = jax.nn.one_hot(lbl, d.shape[-1], dtype=d.dtype)
        ind = onehot * 2 - 1  # +1 at label, -1 elsewhere
        viol = (margin - d * ind) > 0
        if use_linear:
            grad = jnp.where(viol, -ind * reg, 0.0)
        else:
            grad = jnp.where(viol, -2.0 * reg * (margin - d * ind) * ind,
                             0.0)
        return grad.astype(d.dtype), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f


@defop("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """(ref: svm_output.cc) identity forward, hinge-loss backward."""
    f = _svm_output_fn(float(margin), float(regularization_coefficient),
                       bool(use_linear))
    return f(data, label)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------


@defop("Dropout", needs_rng=True, needs_mode=True)
def dropout(data, p=0.5, mode="training", axes=(), _rng=None,
            _training=False):
    """Inverted dropout (ref: src/operator/dropout.cc)."""
    if not _training and mode != "always":
        return data * 1.0
    if p <= 0.0 or _rng is None:
        return data * 1.0
    shape = list(data.shape)
    for a in (axes or ()):
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(_rng, keep, tuple(shape))
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


# ---------------------------------------------------------------------------
# sequence ops (ref: sequence_mask.cc / last.cc / reverse.cc)
# ---------------------------------------------------------------------------


@defop("SequenceMask", variadic=True)
def sequence_mask(*args, use_sequence_length=False, value=0.0, axis=0):
    """Mask positions beyond per-batch lengths. data is (T,B,...) when
    axis=0 or (B,T,...) when axis=1."""
    data = args[0]
    if not use_sequence_length:
        return data * 1.0
    seqlen = args[1]
    T = data.shape[int(axis)]
    t = jnp.arange(T)
    if int(axis) == 0:
        mask = t[:, None] < seqlen[None, :].astype(t.dtype)
    else:
        mask = t[None, :] < seqlen[:, None].astype(t.dtype)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@defop("SequenceLast", variadic=True)
def sequence_last(*args, use_sequence_length=False, axis=0):
    """Select last valid timestep (ref: sequence_last.cc)."""
    data = args[0]
    ax = int(axis)
    if not use_sequence_length:
        return jnp.take(data, data.shape[ax] - 1, axis=ax)
    seqlen = args[1].astype(jnp.int32)
    idx = jnp.clip(seqlen - 1, 0, data.shape[ax] - 1)
    if ax == 0:
        d = jnp.moveaxis(data, 0, 1)  # (B,T,...)
    else:
        d = data
    return jnp.take_along_axis(
        d, idx.reshape((-1, 1) + (1,) * (d.ndim - 2)), axis=1
    ).squeeze(1)


@defop("SequenceReverse", variadic=True)
def sequence_reverse(*args, use_sequence_length=False, axis=0):
    """Reverse along time (T,B,...) honoring lengths (ref:
    sequence_reverse.cc)."""
    data = args[0]
    T = data.shape[0]
    if not use_sequence_length:
        return jnp.flip(data, 0)
    seqlen = args[1].astype(jnp.int32)
    t = jnp.arange(T)[:, None]
    idx = jnp.where(t < seqlen[None, :], seqlen[None, :] - 1 - t, t)
    b = jnp.arange(data.shape[1])[None, :]
    return data[idx, b]


# ---------------------------------------------------------------------------
# spatial ops
# ---------------------------------------------------------------------------


@defop("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Affine/warp sampling-grid generation (ref: grid_generator.cc)."""
    h, w = int(target_shape[0]), int(target_shape[1])
    if transform_type == "affine":
        n = data.shape[0]
        theta = data.reshape((n, 2, 3))
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], 0)
        out = jnp.einsum("nij,jk->nik", theta, coords)
        return out.reshape((n, 2, h, w))
    # warp: data is (n,2,h,w) flow field
    n, _, hh, ww = data.shape
    ys = jnp.arange(hh, dtype=data.dtype)
    xs = jnp.arange(ww, dtype=data.dtype)
    gx, gy = jnp.meshgrid(xs, ys)
    x = (data[:, 0] + gx) * 2.0 / max(ww - 1, 1) - 1.0
    y = (data[:, 1] + gy) * 2.0 / max(hh - 1, 1) - 1.0
    return jnp.stack([x, y], 1)


def _bilinear_sample(data, grid):
    """Shared bilinear sampling core: grid is (n,2,h,w) in [-1,1]."""
    n, c, hin, win = data.shape
    _, _, hout, wout = grid.shape
    x = (grid[:, 0] + 1.0) * (win - 1) / 2.0
    y = (grid[:, 1] + 1.0) * (hin - 1) / 2.0
    x0 = jnp.floor(x); y0 = jnp.floor(y)
    wx = x - x0; wy = y - y0
    def gather(yy, xx):
        yy = jnp.clip(yy, 0, hin - 1).astype(jnp.int32)
        xx = jnp.clip(xx, 0, win - 1).astype(jnp.int32)
        bidx = jnp.arange(n).reshape((n, 1, 1))
        return data[bidx, :, yy, xx]  # (n,hout,wout,c)
    in_bounds = ((x0 >= -1) & (x0 <= win - 1) & (y0 >= -1)
                 & (y0 <= hin - 1)).astype(data.dtype)[..., None]
    v00 = gather(y0, x0); v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0); v11 = gather(y0 + 1, x0 + 1)
    wx_ = wx[..., None]; wy_ = wy[..., None]
    out = ((1 - wy_) * ((1 - wx_) * v00 + wx_ * v01)
           + wy_ * ((1 - wx_) * v10 + wx_ * v11)) * in_bounds
    return jnp.transpose(out, (0, 3, 1, 2))


@defop("BilinearSampler")
def bilinear_sampler(data, grid):
    """(ref: bilinear_sampler.cc) sample data at grid locations."""
    return _bilinear_sample(data, grid)


@defop("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear"):
    """(ref: spatial_transformer.cc) affine STN."""
    grid = grid_generator(loc, "affine", target_shape)
    return _bilinear_sample(data, grid)


@defop("Crop", variadic=True)
def crop_legacy(*args, offset=(0, 0), h_w=(0, 0), num_args=1,
                center_crop=False):
    """Legacy Crop op (ref: crop.cc). Crops args[0] to h_w or to
    args[1]'s spatial size."""
    data = args[0]
    if len(args) > 1:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if center_crop:
        oy = (data.shape[2] - th) // 2
        ox = (data.shape[3] - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy:oy + th, ox:ox + tw]
