"""Operator library: importing this package registers every op.

Single source of truth for the op surface (see registry.py); the
``nd`` and ``sym`` namespaces are generated from it.
"""
from .registry import OPS, OpDef, defop, alias, get_op, find_op, list_ops

# registration side-effects — order matters only for alias targets
from . import elemwise      # noqa: F401
from . import reduce        # noqa: F401
from . import matrix        # noqa: F401
from . import indexing      # noqa: F401
from . import init_op       # noqa: F401
from . import order         # noqa: F401
from . import nn            # noqa: F401
from . import la            # noqa: F401
from . import optimizer_op  # noqa: F401
from . import random_op     # noqa: F401
from . import rnn           # noqa: F401
from . import contrib_det   # noqa: F401
from . import ctc           # noqa: F401
from . import contrib_misc  # noqa: F401
from . import flash         # noqa: F401
from . import moe           # noqa: F401
from ..operator import custom as _custom  # noqa: F401  (registers 'Custom')

__all__ = ["OPS", "OpDef", "defop", "alias", "get_op", "find_op",
           "list_ops"]
