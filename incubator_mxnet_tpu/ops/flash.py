"""Flash attention: a Pallas TPU kernel for the attention hot op.

The role the reference fills with hand-written CUDA for its hot ops
(ref: src/operator/*-inl.cuh), done the TPU way: a tiled
online-softmax kernel (Flash Attention) that keeps the O(L^2) score
matrix out of HBM — each (query-tile, key-tile) block is materialized
only in VMEM, with running max/denominator carried across key tiles.

Registered as the differentiable op ``_flash_attention`` so both the
eager tape and compiled paths use it; the backward recomputes through
the reference XLA attention (memory was the point of the forward; the
backward's FLOPs are the same either way).

On non-TPU backends the kernel runs in Pallas interpret mode (tests
exercise it on CPU); numerics match the reference implementation to
float32 tolerance either way.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import defop

__all__ = ["flash_attention"]

_NEG = -1e30


def _reference_attention(q, k, v, causal, scale):
    """Plain XLA attention, the numeric oracle + backward path.
    q/k/v: (BH, L, D)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        s = jnp.where(mask[None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, bq, bk, nk, causal,
                scale):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
    d = q.shape[-1]
    m = jnp.full((bq, 1), _NEG, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        off = pl.multiple_of(j * bk, bk)   # aligned-slice hint (TPU)
        kb = k_ref[0, pl.ds(off, bk), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(off, bk), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = iq * bq + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = j * bk + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return m_new, l, acc

    if causal:
        # key tiles entirely above the diagonal contribute nothing:
        # bound the loop at the last tile any of this query tile's
        # rows can see (~halves the causal FLOPs)
        upper = jnp.minimum(nk, ((iq + 1) * bq + bk - 1) // bk)
    else:
        upper = nk
    m, l, acc = lax.fori_loop(0, upper, body, (m, l, acc))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, scale, interpret):
    from jax.experimental import pallas as pl

    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = min(128, lq)
    bk = min(128, lk)
    kernel = functools.partial(_fwd_kernel, bq=bq, bk=bk,
                               nk=lk // bk, causal=causal,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(bh, lq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


def _supported(q, k):
    lq, lk = q.shape[1], k.shape[1]
    return (q.ndim == 3 and lq % min(128, lq) == 0
            and lk % min(128, lk) == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, interpret):
    return _flash_fwd(q, k, v, causal, scale, interpret)


def _flash_vjp_fwd(q, k, v, causal, scale, interpret):
    return _flash_fwd(q, k, v, causal, scale, interpret), (q, k, v)


def _flash_vjp_bwd(causal, scale, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _reference_attention(q, k, v, causal, scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@defop("_flash_attention")
def flash_attention(q, k, v, causal=True, scale=None,
                    interpret=None):
    """Tiled online-softmax attention.  q/k/v: (BH, L, D).

    ``interpret`` defaults to True off-TPU (Pallas interpreter) and
    False on TPU (compiled Mosaic kernel).  Falls back to the XLA
    reference implementation for shapes the tiling cannot cover.
    """
    causal = bool(causal)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scale = float(scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not _supported(q, k):
        return _reference_attention(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, bool(interpret))
