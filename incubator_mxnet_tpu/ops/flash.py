"""Flash attention: a Pallas TPU kernel for the attention hot op.

The role the reference fills with hand-written CUDA for its hot ops
(ref: src/operator/*-inl.cuh), done the TPU way: a tiled
online-softmax kernel (Flash Attention) that keeps the O(L^2) score
matrix out of HBM — each (query-tile, key-tile) block is materialized
only in VMEM, with running max/denominator carried across key tiles.

STREAMING design (r5): the key/value (and in the backward, query)
sequence walks through VMEM one block per grid step — the inner grid
dimension is the tile loop, and the online-softmax carry (m, l, acc)
lives in VMEM scratch that persists across grid steps (TPU grids are
sequential).  VMEM use is O(block), independent of sequence length,
so the same kernel covers the long-context regime; the earlier
whole-sequence-staging version hit the ~16 MB VMEM wall near
L*D ~ 2^20 (r4 advisor).

Registered as the differentiable op ``_flash_attention`` so both the
eager tape and compiled paths use it; the backward is the tiled
FlashAttention recipe too — dq/dk/dv kernels rebuild each P tile from
the forward's log-sum-exp residual (delta = rowsum(g*o)), so no L x L
tensor exists in HBM on either direction.

On non-TPU backends the kernel runs in Pallas interpret mode (tests
exercise it on CPU); numerics match the reference implementation to
float32 tolerance either way.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import defop

__all__ = ["flash_attention"]

_NEG = -1e30

# Mosaic's block-tiling rule wants the last two dims of every block
# (8k, 128k)-shaped or equal to the array's; per-row residuals (lse,
# delta) therefore carry a small trailing lane dim instead of being
# (BH, L) vectors — lane 0 holds the value, the rest are broadcast
# copies.  8 sublanes * 4 B is noise next to q/k/v.
_LANES = 8


def _reference_attention(q, k, v, causal, scale, window=0):
    """Plain XLA attention, the numeric oracle + backward path.
    q/k/v: (BH, L, D).  window > 0: sliding-window causal — query i
    attends to keys (i - window, i]."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    lq, lk = q.shape[1], k.shape[1]
    qp = jnp.arange(lq)[:, None]
    kp = jnp.arange(lk)[None, :]
    if causal:
        mask = qp >= kp
        if window > 0:
            mask &= (qp - kp) < window
        s = jnp.where(mask[None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _causal_mask(s, iq, jk, bq, bk, window=0):
    q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = jk * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    keep = q_pos >= k_pos
    if window > 0:
        keep &= (q_pos - k_pos) < window
    return jnp.where(keep, s, _NEG)


def _block_live(iq, jk, bq, bk, causal, window):
    """Does the (q-tile iq, k-tile jk) block hold ANY unmasked pair?
    Dead blocks skip their FLOPs (the grid still steps through)."""
    if not causal:
        return True
    live = jk * bk <= (iq + 1) * bq - 1        # not above diagonal
    if window > 0:
        # below the band: newest key in tile >= oldest in-window key
        live &= (jk + 1) * bk - 1 >= iq * bq - window + 1
    return live


def _band_nj(window, b_res, b_str, n_str):
    """Inner-grid length for banded (sliding-window) iteration: the
    resident tile of size b_res sees at most window + b_res - 1
    streamed positions -> this many b_str-tiles (+1 for alignment),
    capped at the full count."""
    return min(n_str, (b_res + window - 2) // b_str + 2)


def _band_base_k(iq, bq, bk, window):
    """First k-tile of q-tile iq's band (k >= iq*bq - window + 1)."""
    return jnp.maximum((iq * bq - (window - 1)) // bk, 0)


def _band_k_index(iq, j, bq, bk, nk, window):
    """(k-tile, valid) for inner step j of q-tile iq.  Clamped so the
    DMA index stays in range; `valid` excludes clamp duplicates and
    tiles past the causal diagonal."""
    base = _band_base_k(iq, bq, bk, window)
    last = jnp.minimum(((iq + 1) * bq - 1) // bk, nk - 1)
    jk = jnp.minimum(base + j, nk - 1)
    return jk, base + j <= last


def _band_q_index(jk, j, bq, bk, nq, window):
    """(q-tile, valid) for inner step j of k-tile jk (dkv grid)."""
    base = (jk * bk) // bq
    last = jnp.minimum(((jk + 1) * bk - 1 + window - 1) // bq,
                       nq - 1)
    iq = jnp.minimum(base + j, nq - 1)
    return iq, base + j <= last


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc,
                acc_sc, *, bq, bk, nk, nj, causal, scale, window):
    """grid = (BH, NQ, NK): one (q-tile, k-tile) block per step; the
    k dimension is innermost, so the online-softmax carry streams
    through the scratch accumulators."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    j = pl.program_id(2)
    if window > 0:
        # banded: the inner grid walks only the in-window k tiles
        jk, valid = _band_k_index(iq, j, bq, bk, nk, window)
        live = valid
    else:
        jk = j
        live = _block_live(iq, jk, bq, bk, causal, window)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale      # (BQ, D)
        kb = k_ref[0].astype(jnp.float32)             # (BK, D)
        vb = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, iq, jk, bq, bk, window)
        m = m_sc[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_sc[...] = m_new
        l_sc[...] = l_sc[...] * alpha + jnp.sum(p, axis=-1,
                                                keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finalize():
        l = l_sc[...]
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)
        # log-sum-exp residual: what the backward needs to rebuild P
        # tile-by-tile without the L x L score matrix
        lse = m_sc[...][:, 0:1] + jnp.log(l[:, 0:1])   # (BQ, 1)
        lse_ref[0] = jnp.broadcast_to(lse, (bq, _LANES))


def _flash_fwd(q, k, v, causal, scale, interpret, window=0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = min(128, lq)
    bk = min(128, lk)
    nk = lk // bk
    # banded (window > 0): the inner grid covers ONLY in-window k
    # tiles — dead tiles are neither stepped nor DMA'd, so compute
    # AND HBM traffic are O(L * window)
    nj = _band_nj(window, bq, bk, nk) if window > 0 else nk
    if window > 0:
        def kmap(b, i, j):
            return (b, _band_k_index(i, j, bq, bk, nk, window)[0], 0)
    else:
        def kmap(b, i, j):
            return (b, j, 0)
    kernel = functools.partial(_fwd_kernel, bq=bq, bk=bk, nk=nk,
                               nj=nj, causal=causal, scale=scale,
                               window=window)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, lq // bq, nj),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), kmap),
            pl.BlockSpec((1, bk, d), kmap),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, lq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
               dq_ref, dq_sc, *, bq, bk, nk, nj, causal, scale,
               window):
    """grid = (BH, NQ, NK): k/v stream past a resident q tile; dq
    accumulates in scratch."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    j = pl.program_id(2)
    if window > 0:
        jk, live = _band_k_index(iq, j, bq, bk, nk, window)
    else:
        jk = j
        live = _block_live(iq, jk, bq, bk, causal, window)

    @pl.when(j == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)              # (BQ, D)
        g = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]                      # (BQ, 1)
        delta = delta_ref[0][:, 0:1]
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kb.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, jk, bq, bk, window)
        p = jnp.exp(s - lse)
        dp = jnp.dot(g, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_sc[...] = dq_sc[...] + jnp.dot(
            ds, kb, preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finalize():
        dq_ref[0] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, bq, bk, nq, nj,
                causal, scale, window):
    """grid = (BH, NK, NQ): q/g/lse/delta stream past a resident k/v
    tile; dk/dv accumulate in scratch."""
    from jax.experimental import pallas as pl

    jk = pl.program_id(1)
    j = pl.program_id(2)
    if window > 0:
        iq, live = _band_q_index(jk, j, bq, bk, nq, window)
    else:
        iq = j
        live = _block_live(iq, jk, bq, bk, causal, window)

    @pl.when(j == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    @pl.when(live)
    def _step():
        kb = k_ref[0].astype(jnp.float32)             # (BK, D)
        vb = v_ref[0].astype(jnp.float32)
        qb = q_ref[0].astype(jnp.float32)             # (BQ, D)
        gb = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]                      # (BQ, 1)
        delta = delta_ref[0][:, 0:1]
        s = jnp.dot(qb, kb.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, jk, bq, bk, window)
        p = jnp.exp(s - lse)
        dv_sc[...] = dv_sc[...] + jnp.dot(
            p.T, gb, preferred_element_type=jnp.float32)
        dp = jnp.dot(gb, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_sc[...] = dk_sc[...] + jnp.dot(
            ds.T, qb, preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finalize():
        dk_ref[0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, causal, scale, interpret,
               window=0):
    """Tiled backward: rebuilds each P tile from (q, k, lse) — no
    L x L tensor in HBM on the gradient path either (the FlashAttention
    backward recipe: delta = rowsum(g * o), dS = P*(dP - delta))."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = min(128, lq)
    bk = min(128, lk)
    # (BH, LQ, _LANES): lane-padded like lse (Mosaic block tiling)
    delta = jnp.broadcast_to(
        jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1, keepdims=True), (bh, lq, _LANES))
    nk = lk // bk
    nq = lq // bq
    nj_k = _band_nj(window, bq, bk, nk) if window > 0 else nk
    nj_q = _band_nj(window, bk, bq, nq) if window > 0 else nq
    if window > 0:
        def kmap(b, i, j):
            return (b, _band_k_index(i, j, bq, bk, nk, window)[0], 0)

        def qmap(b, jk, j):
            return (b, _band_q_index(jk, j, bq, bk, nq, window)[0],
                    0)
    else:
        def kmap(b, i, j):
            return (b, j, 0)

        def qmap(b, jk, j):
            return (b, j, 0)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, nk=nk,
                          nj=nj_k, causal=causal, scale=scale,
                          window=window),
        grid=(bh, nq, nj_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), kmap),
            pl.BlockSpec((1, bk, d), kmap),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d),
                               lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, nq=nq,
                          nj=nj_q, causal=causal, scale=scale,
                          window=window),
        grid=(bh, nk, nj_q),
        in_specs=[
            pl.BlockSpec((1, bq, d), qmap),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
            pl.BlockSpec((1, bq, d), qmap),
            pl.BlockSpec((1, bq, _LANES), qmap),
            pl.BlockSpec((1, bq, _LANES), qmap),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _supported(q, k):
    """The tiling needs 128-divisible (or single-tile) sequence
    lengths.  VMEM use is O(block) — sequence length is NOT a
    constraint (the r5 streaming kernels; the r4 whole-sequence
    staging hit the VMEM wall near L*D ~ 2^20)."""
    lq, lk = q.shape[1], k.shape[1]
    return (q.ndim == 3 and lq % min(128, lq) == 0
            and lk % min(128, lk) == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, interpret, window):
    return _flash_fwd(q, k, v, causal, scale, interpret, window)[0]


def _flash_vjp_fwd(q, k, v, causal, scale, interpret, window):
    o, lse = _flash_fwd(q, k, v, causal, scale, interpret, window)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, scale, interpret, window, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g, causal, scale, interpret,
                      window)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@defop("_flash_attention")
def flash_attention(q, k, v, causal=True, scale=None,
                    interpret=None, window=0):
    """Tiled online-softmax attention.  q/k/v: (BH, L, D).

    ``interpret`` defaults to True off-TPU (Pallas interpreter) and
    False on TPU (compiled Mosaic kernel).  Falls back to the XLA
    reference implementation for shapes the tiling cannot cover.

    ``window > 0`` (requires ``causal``): sliding-window attention —
    query i sees keys (i - window, i].  Blocks entirely outside the
    band skip their FLOPs, so compute is O(L * window) instead of
    O(L^2 / 2): the long-context local-attention regime (Mistral-
    style) on the same streaming kernels.
    """
    causal = bool(causal)
    window = int(window)
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window > 0 requires causal=True")
    if window and q.shape[1] != k.shape[1]:
        raise ValueError(
            "window > 0 requires self-attention shapes (lq == lk); "
            f"got lq={q.shape[1]}, lk={k.shape[1]} — a query past "
            "the key horizon would have an empty key set")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scale = float(scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not _supported(q, k):
        return _reference_attention(q, k, v, causal, scale,
                                    window=window)
    return _flash(q, k, v, causal, scale, bool(interpret), window)
