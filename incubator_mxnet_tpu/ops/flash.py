"""Flash attention: a Pallas TPU kernel for the attention hot op.

The role the reference fills with hand-written CUDA for its hot ops
(ref: src/operator/*-inl.cuh), done the TPU way: a tiled
online-softmax kernel (Flash Attention) that keeps the O(L^2) score
matrix out of HBM — each (query-tile, key-tile) block is materialized
only in VMEM, with running max/denominator carried across key tiles.

Registered as the differentiable op ``_flash_attention`` so both the
eager tape and compiled paths use it; the backward is the tiled
FlashAttention recipe too — dq/dk/dv kernels rebuild each P tile from
the forward's log-sum-exp residual (delta = rowsum(g*o)), so no L x L
tensor exists in HBM on either direction.

On non-TPU backends the kernel runs in Pallas interpret mode (tests
exercise it on CPU); numerics match the reference implementation to
float32 tolerance either way.
"""
import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax

from .registry import defop

__all__ = ["flash_attention"]

_NEG = -1e30


def _reference_attention(q, k, v, causal, scale):
    """Plain XLA attention, the numeric oracle + backward path.
    q/k/v: (BH, L, D)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        s = jnp.where(mask[None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, bq, bk, nk,
                causal, scale):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
    d = q.shape[-1]
    m = jnp.full((bq, 1), _NEG, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        off = pl.multiple_of(j * bk, bk)   # aligned-slice hint (TPU)
        kb = k_ref[0, pl.ds(off, bk), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(off, bk), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = iq * bq + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = j * bk + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return m_new, l, acc

    if causal:
        # key tiles entirely above the diagonal contribute nothing:
        # bound the loop at the last tile any of this query tile's
        # rows can see (~halves the causal FLOPs)
        upper = jnp.minimum(nk, ((iq + 1) * bq + bk - 1) // bk)
    else:
        upper = nk
    m, l, acc = lax.fori_loop(0, upper, body, (m, l, acc))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # log-sum-exp residual: what the backward needs to rebuild P
    # tile-by-tile without the L x L score matrix
    lse_ref[0] = (m[:, 0] + jnp.log(l[:, 0]))


def _flash_fwd(q, k, v, causal, scale, interpret):
    from jax.experimental import pallas as pl

    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = min(128, lq)
    bk = min(128, lk)
    kernel = functools.partial(_fwd_kernel, bq=bq, bk=bk,
                               nk=lk // bk, causal=causal,
                               scale=scale)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, lq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, lq), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
               dq_ref, *, bq, bk, nk, causal, scale):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                  # (BQ, D)
    g = g_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]                         # (BQ, 1)
    delta = delta_ref[0][:, None]
    dq = jnp.zeros_like(q)

    def body(j, dq):
        off = pl.multiple_of(j * bk, bk)
        kb = k_ref[0, pl.ds(off, bk), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(off, bk), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = iq * bq + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = j * bk + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse)
        dp = jnp.dot(g, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, kb,
                            preferred_element_type=jnp.float32)

    upper = jnp.minimum(nk, ((iq + 1) * bq + bk - 1) // bk) \
        if causal else nk
    dq = lax.fori_loop(0, upper, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, bq, bk, nq, causal, scale):
    from jax.experimental import pallas as pl

    jk = pl.program_id(1)
    kb = k_ref[0].astype(jnp.float32)                 # (BK, D)
    vb = v_ref[0].astype(jnp.float32)
    dk = jnp.zeros_like(kb)
    dv = jnp.zeros_like(vb)

    def body(i, carry):
        dk, dv = carry
        off = pl.multiple_of(i * bq, bq)
        qb = q_ref[0, pl.ds(off, bq), :].astype(jnp.float32)
        gb = g_ref[0, pl.ds(off, bq), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(off, bq)][:, None]
        delta = delta_ref[0, pl.ds(off, bq)][:, None]
        s = jnp.dot(qb, kb.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * bq + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = jk * bk + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse)
        dv = dv + jnp.dot(p.T, gb,
                          preferred_element_type=jnp.float32)
        dp = jnp.dot(gb, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jnp.dot(ds.T, qb,
                          preferred_element_type=jnp.float32)
        return dk, dv

    # causal: q tiles strictly above this k tile's diagonal see none
    # of it — start at the first tile that can attend here
    lower = (jk * bk) // bq if causal else 0
    dk, dv = lax.fori_loop(lower, nq, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, causal, scale, interpret):
    """Tiled backward: rebuilds each P tile from (q, k, lse) — no
    L x L tensor in HBM on the gradient path either (the FlashAttention
    backward recipe: delta = rowsum(g * o), dS = P*(dP - delta))."""
    from jax.experimental import pallas as pl

    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = min(128, lq)
    bk = min(128, lk)
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                           # (BH, LQ)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, nk=lk // bk,
                          causal=causal, scale=scale),
        grid=(bh, lq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, i: (b, i)),
            pl.BlockSpec((1, bq), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, nq=lq // bq,
                          causal=causal, scale=scale),
        grid=(bh, lk // bk),
        in_specs=[
            pl.BlockSpec((1, lq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, lq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, lq), lambda b, j: (b, 0)),
            pl.BlockSpec((1, lq), lambda b, j: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _supported(q, k):
    lq, lk = q.shape[1], k.shape[1]
    if not (q.ndim == 3 and lq % min(128, lq) == 0
            and lk % min(128, lk) == 0):
        return False
    # VMEM ceiling: the kernels stage whole-sequence operands per grid
    # step (fwd/dq: full k+v; dkv: full q+g), i.e. ~2*L*D fp32 plus
    # block-sized buffers.  VMEM is ~16 MB/core; past L*D ~ 2^20
    # (8 MB staged) the backward stops fitting and Mosaic fails to
    # compile or spills (advisor r4).  Longer sequences fall back to
    # the XLA reference — ring attention (parallel/ring_attention.py)
    # is the intended long-context path.
    max_elems = int(os.environ.get("MXTPU_FLASH_MAX_STAGED_ELEMS",
                                   2 ** 20))
    d = q.shape[-1]
    return max(lq, lk) * d <= max_elems


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, interpret):
    return _flash_fwd(q, k, v, causal, scale, interpret)[0]


def _flash_vjp_fwd(q, k, v, causal, scale, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, interpret)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, scale, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g, causal, scale, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@defop("_flash_attention")
def flash_attention(q, k, v, causal=True, scale=None,
                    interpret=None):
    """Tiled online-softmax attention.  q/k/v: (BH, L, D).

    ``interpret`` defaults to True off-TPU (Pallas interpreter) and
    False on TPU (compiled Mosaic kernel).  Falls back to the XLA
    reference implementation for shapes the tiling cannot cover.
    """
    causal = bool(causal)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scale = float(scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not _supported(q, k):
        return _reference_attention(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, bool(interpret))
