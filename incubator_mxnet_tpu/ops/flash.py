"""Flash attention: a Pallas TPU kernel for the attention hot op.

The role the reference fills with hand-written CUDA for its hot ops
(ref: src/operator/*-inl.cuh), done the TPU way: a tiled
online-softmax kernel (Flash Attention) that keeps the O(L^2) score
matrix out of HBM — each (query-tile, key-tile) block is materialized
only in VMEM, with running max/denominator carried across key tiles.

STREAMING design (r5): the key/value (and in the backward, query)
sequence walks through VMEM one block per grid step — the inner grid
dimension is the tile loop, and the online-softmax carry (m, l, acc)
lives in VMEM scratch that persists across grid steps (TPU grids are
sequential).  VMEM use is O(block), independent of sequence length,
so the same kernel covers the long-context regime; the earlier
whole-sequence-staging version hit the ~16 MB VMEM wall near
L*D ~ 2^20 (r4 advisor).

Registered as the differentiable op ``_flash_attention`` so both the
eager tape and compiled paths use it; the backward is the tiled
FlashAttention recipe too — dq/dk/dv kernels rebuild each P tile from
the forward's log-sum-exp residual (delta = rowsum(g*o)), so no L x L
tensor exists in HBM on either direction.

On non-TPU backends the kernel runs in Pallas interpret mode (tests
exercise it on CPU); numerics match the reference implementation to
float32 tolerance either way.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import defop

__all__ = ["flash_attention"]

_NEG = -1e30


def _reference_attention(q, k, v, causal, scale):
    """Plain XLA attention, the numeric oracle + backward path.
    q/k/v: (BH, L, D)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        s = jnp.where(mask[None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _causal_mask(s, iq, jk, bq, bk):
    q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = jk * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc,
                acc_sc, *, bq, bk, nk, causal, scale):
    """grid = (BH, NQ, NK): one (q-tile, k-tile) block per step; the
    k dimension is innermost, so the online-softmax carry streams
    through the scratch accumulators."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    # causal: blocks entirely above the diagonal contribute nothing —
    # skip their FLOPs (the grid still steps through them)
    live = (jk * bk <= (iq + 1) * bq - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale      # (BQ, D)
        kb = k_ref[0].astype(jnp.float32)             # (BK, D)
        vb = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, iq, jk, bq, bk)
        m = m_sc[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_sc[...] = m_new
        l_sc[...] = l_sc[...] * alpha + jnp.sum(p, axis=-1,
                                                keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_sc[...]
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)
        # log-sum-exp residual: what the backward needs to rebuild P
        # tile-by-tile without the L x L score matrix
        lse_ref[0] = m_sc[...][:, 0] + jnp.log(l[:, 0])


def _flash_fwd(q, k, v, causal, scale, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = min(128, lq)
    bk = min(128, lk)
    nk = lk // bk
    kernel = functools.partial(_fwd_kernel, bq=bq, bk=bk, nk=nk,
                               causal=causal, scale=scale)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, lq // bq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, lq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
               dq_ref, dq_sc, *, bq, bk, nk, causal, scale):
    """grid = (BH, NQ, NK): k/v stream past a resident q tile; dq
    accumulates in scratch."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    live = (jk * bk <= (iq + 1) * bq - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)              # (BQ, D)
        g = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, None]
        delta = delta_ref[0][:, None]
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kb.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, jk, bq, bk)
        p = jnp.exp(s - lse)
        dp = jnp.dot(g, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_sc[...] = dq_sc[...] + jnp.dot(
            ds, kb, preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _finalize():
        dq_ref[0] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, bq, bk, nq, causal,
                scale):
    """grid = (BH, NK, NQ): q/g/lse/delta stream past a resident k/v
    tile; dk/dv accumulate in scratch."""
    from jax.experimental import pallas as pl

    jk = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    # causal: q tiles strictly above this k tile's diagonal see none
    # of it
    live = ((iq + 1) * bq - 1 >= jk * bk) if causal else True

    @pl.when(live)
    def _step():
        kb = k_ref[0].astype(jnp.float32)             # (BK, D)
        vb = v_ref[0].astype(jnp.float32)
        qb = q_ref[0].astype(jnp.float32)             # (BQ, D)
        gb = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, None]
        delta = delta_ref[0][:, None]
        s = jnp.dot(qb, kb.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, jk, bq, bk)
        p = jnp.exp(s - lse)
        dv_sc[...] = dv_sc[...] + jnp.dot(
            p.T, gb, preferred_element_type=jnp.float32)
        dp = jnp.dot(gb, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_sc[...] = dk_sc[...] + jnp.dot(
            ds.T, qb, preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, causal, scale, interpret):
    """Tiled backward: rebuilds each P tile from (q, k, lse) — no
    L x L tensor in HBM on the gradient path either (the FlashAttention
    backward recipe: delta = rowsum(g * o), dS = P*(dP - delta))."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = min(128, lq)
    bk = min(128, lk)
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                           # (BH, LQ)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, nk=lk // bk,
                          causal=causal, scale=scale),
        grid=(bh, lq // bq, lk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, d),
                               lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, nq=lq // bq,
                          causal=causal, scale=scale),
        grid=(bh, lk // bk, lq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, j, i: (b, i)),
            pl.BlockSpec((1, bq), lambda b, j, i: (b, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _supported(q, k):
    """The tiling needs 128-divisible (or single-tile) sequence
    lengths.  VMEM use is O(block) — sequence length is NOT a
    constraint (the r5 streaming kernels; the r4 whole-sequence
    staging hit the VMEM wall near L*D ~ 2^20)."""
    lq, lk = q.shape[1], k.shape[1]
    return (q.ndim == 3 and lq % min(128, lq) == 0
            and lk % min(128, lk) == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, interpret):
    return _flash_fwd(q, k, v, causal, scale, interpret)[0]


def _flash_vjp_fwd(q, k, v, causal, scale, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, interpret)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, scale, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g, causal, scale, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@defop("_flash_attention")
def flash_attention(q, k, v, causal=True, scale=None,
                    interpret=None):
    """Tiled online-softmax attention.  q/k/v: (BH, L, D).

    ``interpret`` defaults to True off-TPU (Pallas interpreter) and
    False on TPU (compiled Mosaic kernel).  Falls back to the XLA
    reference implementation for shapes the tiling cannot cover.
    """
    causal = bool(causal)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scale = float(scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not _supported(q, k):
        return _reference_attention(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, bool(interpret))
