"""Reduction and broadcasting ops (ref:
src/operator/tensor/broadcast_reduce_op_value.cc / _index.cc).
"""
import jax.numpy as jnp

from .registry import defop, alias


def _norm_axis(axis, ndim, exclude=False):
    if axis is None or axis == ():
        ax = tuple(range(ndim))
    elif isinstance(axis, int):
        ax = (axis % ndim,)
    else:
        ax = tuple(a % ndim for a in axis)
    if exclude:
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def _make_reduce(name, f):
    def _op(data, axis=None, keepdims=False, exclude=False, _f=f):
        ax = _norm_axis(axis, data.ndim, exclude)
        return _f(data, axis=ax, keepdims=bool(keepdims))
    _op.__name__ = name
    _op.__doc__ = f"Reduce-{name} over axes."
    return _op


for _n, _f in {"sum": jnp.sum, "mean": jnp.mean, "prod": jnp.prod,
               "nansum": jnp.nansum, "nanprod": jnp.nanprod,
               "max": jnp.max, "min": jnp.min}.items():
    defop(_n)(_make_reduce(_n, _f))

alias("sum", "sum_axis")
alias("max", "max_axis")
alias("min", "min_axis")


@defop("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    """L2 (or L1) norm (ref: broadcast_reduce_op_value.cc norm)."""
    ax = None if axis is None else _norm_axis(axis, data.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax,
                            keepdims=bool(keepdims)))


def _make_arg(name, f):
    def _op(data, axis=None, keepdims=False, _f=f):
        if axis is None:
            out = _f(data.reshape(-1), axis=0)
            if keepdims:
                out = out.reshape((1,) * data.ndim)
        else:
            out = _f(data, axis=int(axis))
            if keepdims:
                out = jnp.expand_dims(out, int(axis))
        return out.astype(jnp.result_type(data))
    _op.__name__ = name
    return _op


defop("argmax", differentiable=False)(_make_arg("argmax", jnp.argmax))
defop("argmin", differentiable=False)(_make_arg("argmin", jnp.argmin))


@defop("argmax_channel", differentiable=False)
def argmax_channel(data):
    """argmax over axis 1 (ref: broadcast_reduce_op_index.cc)."""
    return jnp.argmax(data, axis=1).astype(jnp.result_type(data))


@defop("broadcast_axis", aliases=["broadcast_axes"])
def broadcast_axis(data, axis=(), size=()):
    """Broadcast size-1 axes to given sizes."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    shape = list(data.shape)
    for a, s in zip(axes, sizes):
        shape[a % data.ndim] = s
    return jnp.broadcast_to(data, tuple(shape))


@defop("broadcast_to")
def broadcast_to(data, shape=()):
    """Broadcast to an explicit shape; 0 keeps the input dim."""
    tgt = tuple(int(data.shape[i]) if s == 0 else int(s)
                for i, s in enumerate(shape))
    return jnp.broadcast_to(data, tgt)


@defop("broadcast_like")
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)
