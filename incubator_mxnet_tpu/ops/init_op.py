"""Creation ops (ref: src/operator/tensor/init_op.cc)."""
import jax.numpy as jnp

from .registry import defop


def _dt(dtype):
    from ..base import np_dtype
    return np_dtype(dtype or "float32")


@defop("_zeros", aliases=["_sparse_zeros"], differentiable=False)
def _zeros(shape=(), dtype="float32", ctx=None):
    return jnp.zeros(tuple(int(s) for s in shape), _dt(dtype))


@defop("_ones", differentiable=False)
def _ones(shape=(), dtype="float32", ctx=None):
    return jnp.ones(tuple(int(s) for s in shape), _dt(dtype))


@defop("_full", differentiable=False)
def _full(shape=(), value=0.0, dtype="float32", ctx=None):
    return jnp.full(tuple(int(s) for s in shape), value, _dt(dtype))


@defop("_arange", differentiable=False)
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32",
            ctx=None, infer_range=False):
    out = jnp.arange(start, stop, step, _dt(dtype))
    if int(repeat) != 1:
        out = jnp.repeat(out, int(repeat))
    return out


@defop("_eye", differentiable=False)
def _eye(N=0, M=0, k=0, dtype="float32", ctx=None):
    return jnp.eye(int(N), int(M) or None, int(k), dtype=_dt(dtype))
