"""Remaining contrib / legacy op families (VERDICT r2 task 9).

TPU-native implementations of the reference kernels:
  _contrib_fft / _contrib_ifft      (src/operator/contrib/fft.cc,
                                     ifft.cc — cuFFT wrappers)
  _contrib_count_sketch             (contrib/count_sketch.cc)
  _contrib_quantize / _dequantize   (contrib/quantize.cc,
                                     dequantize.cc)
  Correlation                       (src/operator/correlation.cc —
                                     the FlowNet layer)
  _contrib_DeformablePSROIPooling   (contrib/
                                     deformable_psroi_pooling.cc)
  IdentityAttachKLSparseReg         (identity_attach_KL_sparse_reg.cc)
  cast_storage / reshape_like / _sparse_retain / _square_sum and the
  sparse scatter aliases            (tensor/cast_storage.cc,
                                     elemwise_unary_op_basic.cc,
                                     sparse_retain.cc, square_sum.cc)

Everything is jnp/XLA (the FFTs hit XLA's native FFT HLO; Correlation
unrolls the static displacement grid into fused multiply-reduces).
"""
import functools

import jax
import jax.numpy as jnp

from .registry import defop, alias, OPS

# ---------------------------------------------------------------------------
# FFT family
# ---------------------------------------------------------------------------


@defop("_contrib_fft")
def contrib_fft(data, compute_size=128):
    """FFT along the last axis; complex output interleaved as
    [r0, i0, r1, i1, ...] -> (..., 2d) (ref: contrib/fft-inl.h).
    ``compute_size`` (the reference's batching knob) is accepted and
    ignored — XLA tiles the batch itself."""
    f = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(data.dtype)


@defop("_contrib_ifft")
def contrib_ifft(data, compute_size=128):
    """Unnormalized inverse FFT of interleaved complex input:
    out = n * ifft(in) (cuFFT inverse applies no 1/n, and the
    reference passes it through — ref: contrib/ifft-inl.h)."""
    d = data.shape[-1] // 2
    c = data.reshape(data.shape[:-1] + (d, 2))
    z = c[..., 0] + 1j * c[..., 1]
    out = jnp.fft.ifft(z, axis=-1).real * d
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# count sketch
# ---------------------------------------------------------------------------


@defop("_contrib_count_sketch")
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection (ref: contrib/count_sketch-inl.h):
    out[n, h[j]] += s[j] * data[n, j]."""
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    signed = data * ss[None, :]
    out = jnp.zeros((data.shape[0], int(out_dim)), data.dtype)
    return out.at[:, hh].add(signed)


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------


@defop("_contrib_quantize", num_outputs=3, differentiable=False)
def quantize(data, min_range, max_range, out_type="uint8"):
    """Linear quantization to uint8 over [min_range, max_range]
    (ref: contrib/quantize-inl.h QuantizeCompute — the reference
    kernel supports only uint8 too)."""
    if out_type != "uint8":
        raise ValueError(
            f"_contrib_quantize supports out_type='uint8' only "
            f"(like the reference kernel); got {out_type!r}")
    lo = min_range.reshape(())
    hi = max_range.reshape(())
    scale = 255.0 / (hi - lo)
    q = jnp.clip(jnp.round((data - lo) * scale), 0, 255)
    return (q.astype(jnp.uint8), min_range * 1.0, max_range * 1.0)


@defop("_contrib_dequantize", differentiable=False)
def dequantize(data, min_range, max_range, out_type="float32"):
    """(ref: contrib/dequantize-inl.h)"""
    lo = min_range.reshape(())
    hi = max_range.reshape(())
    scale = (hi - lo) / 255.0
    return data.astype(jnp.float32) * scale + lo


# ---------------------------------------------------------------------------
# Correlation (FlowNet)
# ---------------------------------------------------------------------------


@defop("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (ref: src/operator/
    correlation-inl.h).  For every output position and every
    displacement (dy,dx) on the stride2 grid within
    max_displacement, correlates a kernel_size^2 patch of data1 with
    the displaced patch of data2, averaged over channels*K^2.
    Output: (B, D*D, out_h, out_w), displacement-major like the
    reference (dy slow, dx fast).  The static D^2 loop unrolls into
    fused multiply-reduces under jit."""
    b, c, h, w = data1.shape
    K = int(kernel_size)
    pad = int(pad_size)
    md = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    d2 = md // s2
    # pad both inputs
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    kr = K // 2
    border = kr + md
    ph, pw = h + 2 * pad, w + 2 * pad
    out_h = (ph - 2 * border + s1 - 1) // s1
    out_w = (pw - 2 * border + s1 - 1) // s1
    ys = border + s1 * jnp.arange(out_h)
    xs = border + s1 * jnp.arange(out_w)

    combine = ((lambda a, b: a * b) if is_multiply
               else (lambda a, b: jnp.abs(a - b)))
    outs = []
    for dy in range(-d2 * s2, d2 * s2 + 1, s2):
        for dx in range(-d2 * s2, d2 * s2 + 1, s2):
            # correlate channel-wise then mean over c*K^2
            acc = 0
            for ky in range(-kr, K - kr):
                for kx in range(-kr, K - kr):
                    rows = ys + ky
                    cols = xs + kx
                    a = p1[:, :, rows][:, :, :, cols]
                    bb = p2[:, :, rows + dy][:, :, :, cols + dx]
                    acc = acc + combine(a, bb).sum(axis=1)
            outs.append(acc / (c * K * K))
    return jnp.stack(outs, axis=1).astype(data1.dtype)


# ---------------------------------------------------------------------------
# deformable PS-ROI pooling
# ---------------------------------------------------------------------------


@defop("_contrib_DeformablePSROIPooling", variadic=True,
       num_outputs=1)
def deformable_psroi_pooling(*inputs, spatial_scale=1.0, output_dim=1,
                             group_size=1, pooled_size=1, part_size=0,
                             sample_per_part=1, trans_std=0.0,
                             no_trans=False):
    """Deformable position-sensitive ROI pooling (ref: contrib/
    deformable_psroi_pooling-inl.h; R-FCN + Deformable ConvNets).

    inputs: data (B, output_dim*group_size^2, H, W), rois (R, 5)
    [batch_idx, x0, y0, x1, y1] in image coords, and unless
    ``no_trans`` a trans tensor (R, 2*cls, part, part) of normalized
    bin offsets.  Output (R, output_dim, pooled, pooled)."""
    data, rois = inputs[0], inputs[1]
    trans = None if (no_trans or len(inputs) < 3) else inputs[2]
    B, C, H, W = data.shape
    g = int(group_size)
    p = int(pooled_size)
    part = int(part_size) if part_size else p
    spp = int(sample_per_part)
    odim = int(output_dim)

    def one_roi(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        x0 = roi[1] * spatial_scale - 0.5
        y0 = roi[2] * spatial_scale - 0.5
        x1 = roi[3] * spatial_scale + 0.5
        y1 = roi[4] * spatial_scale + 0.5
        rw = jnp.maximum(x1 - x0, 0.1)
        rh = jnp.maximum(y1 - y0, 0.1)
        bw, bh = rw / p, rh / p
        img = data[bidx]                      # (C, H, W)
        sub = bw / (spp + 1.0)
        sbh = bh / (spp + 1.0)
        ods = jnp.arange(odim)
        # per-class deformation offsets (ref: class_id = ctop /
        # channels_each_class, trans channels [2*cls, 2*cls+1])
        n_cls = 1 if tr is None else tr.shape[0] // 2
        cec = max(odim // max(n_cls, 1), 1)
        cls_ids = ods // cec
        outs = jnp.zeros((odim, p, p), data.dtype)
        for py in range(p):
            for px in range(p):
                pt_y = min(py * part // p, part - 1)
                pt_x = min(px * part // p, part - 1)
                if tr is None:
                    dx = dy = jnp.zeros((odim,), jnp.float32)
                else:
                    dx = tr[cls_ids * 2, pt_y, pt_x] \
                        * trans_std * rw
                    dy = tr[cls_ids * 2 + 1, pt_y, pt_x] \
                        * trans_std * rh
                gy = min(py * g // p, g - 1)
                gx = min(px * g // p, g - 1)
                # ctop-major channel map, same as PSROIPooling:
                # input channel = (ctop*g + gy)*g + gx
                chans = (ods * g + gy) * g + gx
                acc = jnp.zeros((odim,), data.dtype)
                for iy in range(1, spp + 1):
                    for ix in range(1, spp + 1):
                        sy = y0 + py * bh + iy * sbh + dy
                        sx = x0 + px * bw + ix * sub + dx
                        syc = jnp.clip(sy, 0.0, H - 1.0)
                        sxc = jnp.clip(sx, 0.0, W - 1.0)
                        yl = jnp.floor(syc).astype(jnp.int32)
                        xl = jnp.floor(sxc).astype(jnp.int32)
                        yh = jnp.minimum(yl + 1, H - 1)
                        xh = jnp.minimum(xl + 1, W - 1)
                        wy = syc - yl
                        wx = sxc - xl
                        v = ((1 - wy) * (1 - wx) * img[chans, yl, xl]
                             + (1 - wy) * wx * img[chans, yl, xh]
                             + wy * (1 - wx) * img[chans, yh, xl]
                             + wy * wx * img[chans, yh, xh])
                        inb = ((sy > -1) & (sy < H) & (sx > -1)
                               & (sx < W)).astype(data.dtype)
                        acc = acc + v * inb
                outs = outs.at[:, py, px].set(acc / (spp * spp))
        return outs

    if trans is None:
        return jax.vmap(lambda r: one_roi(r, None))(rois)
    return jax.vmap(one_roi)(rois, trans)


# ---------------------------------------------------------------------------
# loss attachments
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _kl_sparse_fn(sparseness_target, penalty):
    @jax.custom_vjp
    def f(data):
        return data * 1.0

    def fwd(data):
        return data * 1.0, data

    def bwd(data, g):
        # KL sparsity penalty on the mean activation per hidden unit
        # (ref: identity_attach_KL_sparse_reg-inl.h; divergence: the
        # batch mean stands in for the momentum moving average)
        rho = jnp.clip(jnp.mean(data, axis=0), 1e-6, 1 - 1e-6)
        t = sparseness_target
        kl = (-t / rho + (1 - t) / (1 - rho)) / data.shape[0]
        return (g + penalty * kl[None, :].astype(g.dtype),)

    f.defvjp(fwd, bwd)
    return f


@defop("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity that adds a KL sparseness-penalty gradient
    (ref: src/operator/identity_attach_KL_sparse_reg.cc)."""
    return _kl_sparse_fn(float(sparseness_target), float(penalty))(data)


# ---------------------------------------------------------------------------
# storage / shape utilities
# ---------------------------------------------------------------------------


@defop("cast_storage", aliases=["_sparse_cast_storage"])
def cast_storage_op(data, stype="default"):
    """Graph-level storage cast (ref: tensor/cast_storage.cc).  In
    jnp graphs every tensor is dense, so 'default' is the identity;
    sparse targets exist only on the imperative NDArray surface
    (``arr.tostype`` / ``nd.sparse.cast_storage``)."""
    if stype != "default":
        raise ValueError(
            "cast_storage inside a compiled graph supports only "
            "stype='default' (XLA tensors are dense); use "
            "NDArray.tostype / nd.sparse.cast_storage imperatively")
    return data * 1.0


@defop("reshape_like")
def reshape_like(lhs, rhs):
    """(ref: tensor/elemwise_unary_op_basic.cc reshape_like)"""
    return lhs.reshape(rhs.shape)


@defop("_sparse_retain")
def sparse_retain_op(data, indices):
    """Dense-graph semantics of sparse_retain (ref: tensor/
    sparse_retain.cc): rows whose index is absent become zero."""
    idx = indices.reshape(-1).astype(jnp.int32)
    keep = (jnp.arange(data.shape[0])[:, None] == idx[None, :]) \
        .any(axis=1)
    return data * keep.reshape((-1,) + (1,) * (data.ndim - 1)) \
        .astype(data.dtype)


@defop("_square_sum")
def square_sum(data, axis=None, keepdims=False):
    """(ref: tensor/square_sum-inl.h — the sparse-optimized
    sum(x^2); dense here, XLA fuses the square into the reduce)"""
    ax = axis if axis is None else int(axis)
    return jnp.sum(jnp.square(data), axis=ax, keepdims=bool(keepdims))


@defop("_scatter_elemwise_div")
def scatter_elemwise_div(lhs, rhs):
    """(ref: tensor/elemwise_binary_op_basic.cc scatter alias —
    storage-aware division; dense math is identical)"""
    return lhs / rhs


@defop("_scatter_plus_scalar")
def scatter_plus_scalar(data, scalar=0.0):
    return data + scalar


@defop("_scatter_minus_scalar")
def scatter_minus_scalar(data, scalar=0.0):
    return data - scalar


# legacy plugin hooks: the Custom op is the supported extension point
@defop("_NDArray", differentiable=False)
def _ndarray_plugin(*args, **kwargs):
    """Legacy NDArray-function plugin hook (ref: plugin/). Python
    extension ops use operator.CustomOp here."""
    raise NotImplementedError(
        "_NDArray plugin ops are not supported; implement a Custom "
        "op (incubator_mxnet_tpu.operator.CustomOp) instead")


@defop("_Native", differentiable=False)
def _native_plugin(*args, **kwargs):
    """Legacy native-callback plugin hook (ref: plugin/)."""
    raise NotImplementedError(
        "_Native plugin ops are not supported; implement a Custom "
        "op (incubator_mxnet_tpu.operator.CustomOp) instead")


# MakeLoss: the op-property loss head (ref: src/operator/
# make_loss.cc) — forward identity, backward grad_scale (optionally
# normalized), independent of the incoming cotangent
@functools.lru_cache(maxsize=None)
def _make_loss_fn(grad_scale, valid_thresh, normalization):
    @jax.custom_vjp
    def f(data):
        return data * 1.0

    def fwd(data):
        return data * 1.0, data

    def bwd(data, g):
        scale = grad_scale
        if normalization == "batch":
            scale = scale / data.shape[0]
        grad = jnp.full(data.shape, scale, data.dtype)
        if normalization == "valid":
            valid = jnp.maximum(
                jnp.sum((data > valid_thresh).astype(data.dtype)), 1.0)
            grad = grad / valid
        return (grad,)

    f.defvjp(fwd, bwd)
    return f


def _make_loss_head(data, grad_scale=1.0, valid_thresh=0.0,
                    normalization="null"):
    """(ref: src/operator/make_loss.cc MakeLossOp)"""
    return _make_loss_fn(float(grad_scale), float(valid_thresh),
                         str(normalization))(data)


# upgrade the plain 'make_loss' registration in elemwise.py to the
# loss-head gradient semantics and add the legacy name
OPS["make_loss"].fn = _make_loss_head
alias("make_loss", "MakeLoss")
