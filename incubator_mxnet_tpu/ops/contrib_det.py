"""Detection / contrib operator family, TPU-first.

Covers the reference's SSD + R-CNN op set (ref:
src/operator/contrib/multibox_prior.cc, multibox_target.cc,
multibox_detection.cc, src/operator/roi_pooling.cc,
src/operator/contrib/proposal.cc, psroi_pooling.cu,
deformable_convolution-inl.h) re-designed for XLA:

- every kernel is fixed-shape and jit-safe: NMS and bipartite
  matching run as `lax.scan`/`lax.while_loop` with masking instead of
  data-dependent compaction — output rows that the reference drops
  are marked (class = -1) rather than removed;
- sorting/mining use stable `argsort` rank masks instead of host-side
  std::stable_sort;
- ROI kernels pool via bin-membership masks (two-stage reductions)
  so XLA sees dense reductions, not scatter loops;
- deformable convolution is bilinear-gather im2col + one MXU matmul.
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import defop

__all__ = []


def _tuple(v, n=None, dtype=float):
    """Normalize tuple-ish params (accepts tuple/list/str)."""
    if isinstance(v, str):
        v = v.strip("()[] ")
        v = tuple(dtype(t) for t in v.split(",") if t.strip())
    elif isinstance(v, (int, float)):
        v = (dtype(v),)
    else:
        v = tuple(dtype(t) for t in v)
    if n is not None and len(v) == 1:
        v = v * n
    return v


def _iou_corner(a, b):
    """IoU between corner boxes a (A,4) and b (L,4) -> (A,L); matches
    reference safe_divide semantics (union<=0 -> 0)."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0),
                     0.0)


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------

@defop("_contrib_MultiBoxPrior", differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Generate prior (anchor) boxes from a feature map (ref:
    src/operator/contrib/multibox_prior-inl.h MultiBoxPriorForward).
    data: (B, C, H, W) -> (1, H*W*num_anchors, 4) corner boxes."""
    sizes = _tuple(sizes)
    ratios = _tuple(ratios)
    steps = _tuple(steps, 2)
    offsets = _tuple(offsets, 2)
    in_h, in_w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / in_h
    step_x = steps[1] if steps[1] > 0 else 1.0 / in_w

    cy = (jnp.arange(in_h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(in_w, dtype=jnp.float32) + offsets[1]) * step_x

    # per-location anchors: all sizes at ratio 1, then ratios[1:] at
    # sizes[0] — (size * H / W) keeps squares square in pixel space
    ws, hs = [], []
    for s in sizes:
        ws.append(s * in_h / in_w / 2.0)
        hs.append(s / 2.0)
    for r in ratios[1:]:
        sq = float(r) ** 0.5
        ws.append(sizes[0] * in_h / in_w * sq / 2.0)
        hs.append(sizes[0] / sq / 2.0)
    w = jnp.asarray(ws, jnp.float32)    # (K,)
    h = jnp.asarray(hs, jnp.float32)

    cxg = cx[None, :, None]             # (1, W, 1)
    cyg = cy[:, None, None]             # (H, 1, 1)
    boxes = jnp.stack(jnp.broadcast_arrays(
        cxg - w, cyg - h, cxg + w, cyg + h), axis=-1)  # (H, W, K, 4)
    out = boxes.reshape(1, -1, 4).astype(data.dtype)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------

def _mbt_one(anchors, lab, cls_pred, overlap_threshold, ignore_label,
             neg_ratio, neg_thresh, variances):
    """Single-batch-item target assignment (ref:
    src/operator/contrib/multibox_target.cc MultiBoxTargetForward)."""
    A = anchors.shape[0]
    L = lab.shape[0]
    f32 = jnp.float32

    valid = jnp.cumprod((lab[:, 0] != -1.0).astype(jnp.int32)) == 1
    n_valid = valid.sum()
    gt = lab[:, 1:5]
    overlaps = jnp.where(valid[None, :], _iou_corner(anchors, gt), -1.0)

    # ---- stage 1: greedy bipartite matching (<= L rounds) ----------
    def round_fn(carry, _):
        aflag, agt, aiou, gused = carry
        mask = (aflag != 1)[:, None] & (~gused)[None, :] & valid[None, :]
        masked = jnp.where(mask, overlaps, -1.0)
        flat = jnp.argmax(masked)
        bi, bj = flat // L, flat % L
        best = masked.reshape(-1)[flat]
        do = best > 1e-6
        aflag = aflag.at[bi].set(jnp.where(do, 1, aflag[bi]))
        agt = agt.at[bi].set(jnp.where(do, bj, agt[bi]))
        aiou = aiou.at[bi].set(jnp.where(do, best, aiou[bi]))
        gused = gused.at[bj].set(jnp.where(do, True, gused[bj]))
        return (aflag, agt, aiou, gused), None

    init = (jnp.full((A,), -1, jnp.int32),          # anchor flag
            jnp.zeros((A,), jnp.int32),             # matched gt
            jnp.full((A,), -1.0, f32),              # matched iou
            jnp.zeros((L,), bool))                  # gt used
    (aflag, agt, aiou, _), _ = lax.scan(round_fn, init, None, length=L)

    # ---- stage 2: per-anchor best gt + threshold positives ---------
    best_iou = overlaps.max(axis=1)                 # (A,)
    best_gt = jnp.argmax(overlaps, axis=1)
    if overlap_threshold > 0:
        promote = (aflag != 1) & (best_iou > overlap_threshold)
        agt = jnp.where(promote, best_gt, agt)
        aiou = jnp.where(promote, best_iou, aiou)
        aflag = jnp.where(promote, 1, aflag)

    positive = aflag == 1
    num_pos = positive.sum()

    # ---- stage 3: negatives (hard mining or all) -------------------
    if neg_ratio > 0:
        num_neg = jnp.minimum((num_pos * neg_ratio).astype(jnp.int32),
                              A - num_pos)
        # background prob per anchor; hardest negatives = lowest prob
        probs = jax.nn.softmax(cls_pred.astype(f32), axis=0)[0]  # (A,)
        cand = (~positive) & (best_iou < neg_thresh)
        key = jnp.where(cand, probs, jnp.inf)
        rank = jnp.argsort(jnp.argsort(key, stable=True), stable=True)
        negative = cand & (rank < num_neg)
    else:
        negative = ~positive

    # ---- emit targets ----------------------------------------------
    cls_t = jnp.where(positive, lab[agt, 0] + 1.0,
                      jnp.where(negative, 0.0, float(ignore_label)))

    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    g = gt[agt]                                     # (A, 4)
    gx = (g[:, 0] + g[:, 2]) * 0.5
    gy = (g[:, 1] + g[:, 3]) * 0.5
    gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-12)
    gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-12)
    vx, vy, vw, vh = variances
    enc = jnp.stack([(gx - ax) / aw / vx, (gy - ay) / ah / vy,
                     jnp.log(gw / aw) / vw, jnp.log(gh / ah) / vh],
                    axis=1)                         # (A, 4)
    loc_t = jnp.where(positive[:, None], enc, 0.0).reshape(-1)
    loc_m = jnp.where(positive[:, None],
                      jnp.ones((A, 4), f32), 0.0).reshape(-1)

    # no valid gt in this image -> everything stays at init values
    has_gt = n_valid > 0
    loc_t = jnp.where(has_gt, loc_t, 0.0)
    loc_m = jnp.where(has_gt, loc_m, 0.0)
    cls_t = jnp.where(has_gt, cls_t, float(ignore_label))
    return loc_t, loc_m, cls_t


@defop("_contrib_MultiBoxTarget", num_outputs=3, differentiable=False,
       cache_vjp=True)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5,
                    minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training target assignment (ref:
    src/operator/contrib/multibox_target-inl.h).
    anchor (1, A, 4), label (B, L, >=5), cls_pred (B, C, A) ->
    loc_target (B, 4A), loc_mask (B, 4A), cls_target (B, A).

    ``minimum_negative_samples`` is accepted but unused, exactly like
    the reference kernel (multibox_target.cc:185 derives num_negative
    from num_positive * ratio only)."""
    variances = _tuple(variances, 4)
    anchors = anchor.reshape(-1, 4).astype(jnp.float32)
    lab = label.astype(jnp.float32)
    loc_t, loc_m, cls_t = jax.vmap(
        lambda lb, cp: _mbt_one(anchors, lb, cp,
                                float(overlap_threshold),
                                float(ignore_label),
                                float(negative_mining_ratio),
                                float(negative_mining_thresh),
                                variances))(lab, cls_pred)
    dt = label.dtype
    return loc_t.astype(dt), loc_m.astype(dt), cls_t.astype(dt)


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------

def _decode_boxes(anchors, loc, variances, clip):
    """Inverse of the loc encoding (ref: multibox_detection-inl.h
    TransformLocations)."""
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    vx, vy, vw, vh = variances
    ox = loc[:, 0] * vx * aw + ax
    oy = loc[:, 1] * vy * ah + ay
    ow = jnp.exp(loc[:, 2] * vw) * aw * 0.5
    oh = jnp.exp(loc[:, 3] * vh) * ah * 0.5
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _mbd_one(cls_prob, loc_pred, anchors, threshold, clip, variances,
             nms_threshold, force_suppress, nms_topk):
    A = anchors.shape[0]
    scores_fg = cls_prob[1:, :]                     # (C-1, A)
    score = scores_fg.max(axis=0)
    cid = jnp.argmax(scores_fg, axis=0) + 1         # 1-based class
    cid = jnp.where(score < threshold, 0, cid)
    valid = cid > 0
    n_valid = valid.sum()
    boxes = _decode_boxes(anchors, loc_pred.reshape(A, 4), variances,
                          clip)

    # order: valid rows first, sorted by score descending (stable)
    key = jnp.where(valid, -score, jnp.inf)
    order = jnp.argsort(key, stable=True)
    cls_s = (cid[order] - 1).astype(jnp.float32)
    score_s = score[order]
    boxes_s = boxes[order]
    present = valid[order]                          # prefix of True

    # NMS candidate window: top-k rows only, so the pairwise IoU is
    # (k, k) not (A, A) — for SSD300 (A=8732, nms_topk=400) that is
    # the difference between 0.6 MB and 305 MB per image
    k = A if nms_topk <= 0 else min(int(nms_topk), A)
    rank_k = jnp.arange(k)
    nkeep = jnp.minimum(n_valid, k)
    in_nms = present[:k] & (rank_k < nkeep)
    b_k = boxes_s[:k]
    c_k = cls_s[:k]

    iou = _iou_corner(b_k, b_k)                     # (k, k)
    may_sup = iou >= nms_threshold
    if not force_suppress:
        may_sup = may_sup & (c_k[:, None] == c_k[None, :])
    may_sup = may_sup & (rank_k[None, :] > rank_k[:, None]) \
        & in_nms[:, None] & in_nms[None, :]

    def cond(st):
        return st[0] < nkeep

    def body(st):
        i, alive = st
        return i + 1, jnp.where(alive[i], alive & ~may_sup[i], alive)

    _, alive_k = lax.while_loop(cond, body, (jnp.int32(0), in_nms))

    alive = jnp.zeros((A,), bool).at[:k].set(alive_k)
    out_cls = jnp.where(alive, cls_s, -1.0)
    out = jnp.concatenate([out_cls[:, None], score_s[:, None],
                           boxes_s], axis=1)        # (A, 6)
    return jnp.where(present[:, None], out, -1.0)


@defop("_contrib_MultiBoxDetection", differentiable=False,
       cache_vjp=True)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0,
                       nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + per-class NMS for SSD inference (ref:
    src/operator/contrib/multibox_detection-inl.h).  Output (B, A, 6)
    rows [class_id, score, xmin, ymin, xmax, ymax]; suppressed /
    invalid rows carry class_id = -1.

    Divergence from the reference: with ``nms_topk`` > 0 the reference
    leaves stale duplicate rows between topk and valid_count; here
    those rows are marked suppressed instead.  ``background_id`` is
    accepted but class 0 is always background, exactly like the
    reference kernel (multibox_detection.cc iterates classes from 1
    and never reads the param)."""
    variances = _tuple(variances, 4)
    anchors = anchor.reshape(-1, 4).astype(jnp.float32)
    out = jax.vmap(
        lambda cp, lp: _mbd_one(cp.astype(jnp.float32),
                                lp.astype(jnp.float32), anchors,
                                float(threshold), bool(clip), variances,
                                float(nms_threshold),
                                bool(force_suppress),
                                int(nms_topk)))(cls_prob, loc_pred)
    return out.astype(cls_prob.dtype)


# ---------------------------------------------------------------------------
# ROIPooling
# ---------------------------------------------------------------------------

def _bin_masks(start, end, pooled, extent):
    """Membership masks (pooled, extent) of [start_p, end_p) bins."""
    p = jnp.arange(pooled, dtype=jnp.float32)
    idx = jnp.arange(extent)
    lo = jnp.clip(start(p), 0, extent).astype(jnp.int32)
    hi = jnp.clip(end(p), 0, extent).astype(jnp.int32)
    return (idx[None, :] >= lo[:, None]) & (idx[None, :] < hi[:, None])


@defop("ROIPooling", aliases=("_contrib_ROIPooling",))
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """Max pooling over quantized ROI bins (ref:
    src/operator/roi_pooling.cc ROIPoolForward).
    data (B, C, H, W), rois (R, 5) [batch_idx, x1, y1, x2, y2] ->
    (R, C, ph, pw)."""
    pooled_size = _tuple(pooled_size, 2, int)
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    B, C, H, W = data.shape
    scale = float(spatial_scale)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        bh, bw = rh / ph, rw / pw

        mh = _bin_masks(lambda p: jnp.floor(p * bh) + y1,
                        lambda p: jnp.ceil((p + 1) * bh) + y1, ph, H)
        mw = _bin_masks(lambda p: jnp.floor(p * bw) + x1,
                        lambda p: jnp.ceil((p + 1) * bw) + x1, pw, W)
        x = jnp.take(data, b, axis=0)               # (C, H, W)
        neg = jnp.finfo(data.dtype).min
        # two-stage masked max: W then H
        t = jnp.where(mw[None, None, :, :], x[:, :, None, :], neg)
        t = t.max(axis=3)                           # (C, H, pw)
        t = jnp.where(mh[None, :, :, None], t[:, None, :, :], neg)
        out = t.max(axis=2)                         # (C, ph, pw)
        empty = (~mh.any(axis=1))[:, None] | (~mw.any(axis=1))[None, :]
        return jnp.where(empty[None], 0.0, out).astype(data.dtype)

    return jax.vmap(one)(rois.astype(jnp.float32))


# ---------------------------------------------------------------------------
# PSROIPooling
# ---------------------------------------------------------------------------

@defop("_contrib_PSROIPooling")
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                  pooled_size=1, group_size=0):
    """Position-sensitive ROI average pooling, R-FCN style (ref:
    src/operator/contrib/psroi_pooling.cu PSROIPoolForwardKernel).
    data (B, C=output_dim*g*g, H, W), rois (R, 5) ->
    (R, output_dim, p, p)."""
    p = int(pooled_size)
    g = int(group_size) if int(group_size) > 0 else p
    od = int(output_dim)
    B, C, H, W = data.shape
    scale = float(spatial_scale)

    # channel map: out channel ct at bin (ph, pw) reads input channel
    # (ct*g + gh)*g + gw
    phs = jnp.arange(p)
    gh = jnp.clip((phs * g) // p, 0, g - 1)
    chan = ((jnp.arange(od)[:, None, None] * g + gh[None, :, None]) * g
            + gh[None, None, :])                    # (od, p, p)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * scale
        y1 = jnp.round(roi[2]) * scale
        x2 = (jnp.round(roi[3]) + 1.0) * scale
        y2 = (jnp.round(roi[4]) + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / p, rw / p

        mh = _bin_masks(lambda q: jnp.floor(q * bh + y1),
                        lambda q: jnp.ceil((q + 1) * bh + y1), p, H)
        mw = _bin_masks(lambda q: jnp.floor(q * bw + x1),
                        lambda q: jnp.ceil((q + 1) * bw + x1), p, W)
        x = jnp.take(data, b, axis=0).astype(jnp.float32)  # (C,H,W)
        # sums over bins for every channel: (C, p, p)
        sums = jnp.einsum("chw,ph,qw->cpq", x,
                          mh.astype(jnp.float32), mw.astype(jnp.float32))
        cnt = (mh.sum(1)[:, None] * mw.sum(1)[None, :]).astype(
            jnp.float32)                            # (p, p)
        avg = jnp.where(cnt > 0, sums / jnp.maximum(cnt, 1.0), 0.0)
        return avg[chan, jnp.arange(p)[None, :, None],
                   jnp.arange(p)[None, None, :]].astype(data.dtype)

    return jax.vmap(one)(rois.astype(jnp.float32))


# ---------------------------------------------------------------------------
# Proposal / MultiProposal
# ---------------------------------------------------------------------------

def _gen_base_anchors(stride, scales, ratios):
    """(ref: proposal-inl.h GenerateAnchors — note the reference's
    floor/round quantisation is reproduced exactly)."""
    import numpy as np
    base = np.array([0.0, 0.0, stride - 1.0, stride - 1.0])
    w = base[2] - base[0] + 1.0
    h = base[3] - base[1] + 1.0
    x_ctr = base[0] + 0.5 * (w - 1.0)
    y_ctr = base[1] + 0.5 * (h - 1.0)
    size = w * h
    out = []
    for r in ratios:
        size_r = np.floor(size / r)
        new_w = np.floor(np.sqrt(size_r) + 0.5)
        new_h = np.floor((new_w * r) + 0.5)
        for s in scales:
            ws, hs = new_w * s, new_h * s
            out.append([x_ctr - 0.5 * (ws - 1.0), y_ctr - 0.5 * (hs - 1.0),
                        x_ctr + 0.5 * (ws - 1.0), y_ctr + 0.5 * (hs - 1.0)])
    return jnp.asarray(out, jnp.float32)            # (K, 4)


def _proposal_one(fg_scores, bbox_deltas, im_info, base_anchors,
                  stride, pre_n, post_n, thresh, min_size):
    """fg_scores (K, H, W), bbox_deltas (4K, H, W), im_info (3,)."""
    K, H, W = fg_scores.shape
    # shifted anchors, layout index = h*(W*K) + w*K + k
    shift_x = jnp.arange(W, dtype=jnp.float32) * stride
    shift_y = jnp.arange(H, dtype=jnp.float32) * stride
    anc = (base_anchors[None, None, :, :]
           + jnp.stack(jnp.broadcast_arrays(
               shift_x[None, :, None], shift_y[:, None, None],
               shift_x[None, :, None], shift_y[:, None, None]),
               axis=-1))                            # (H, W, K, 4)
    anc = anc.reshape(-1, 4)
    deltas = bbox_deltas.reshape(K, 4, H, W).transpose(2, 3, 0, 1) \
        .reshape(-1, 4)                             # same ordering
    scores = fg_scores.transpose(1, 2, 0).reshape(-1)

    im_h, im_w, im_scale = im_info[0], im_info[1], im_info[2]
    aw = anc[:, 2] - anc[:, 0] + 1.0
    ah = anc[:, 3] - anc[:, 1] + 1.0
    ax = anc[:, 0] + 0.5 * (aw - 1.0)
    ay = anc[:, 1] + 0.5 * (ah - 1.0)
    px = deltas[:, 0] * aw + ax
    py = deltas[:, 1] * ah + ay
    pw = jnp.exp(deltas[:, 2]) * aw
    phh = jnp.exp(deltas[:, 3]) * ah
    x1 = jnp.clip(px - 0.5 * (pw - 1.0), 0.0, im_w - 1.0)
    y1 = jnp.clip(py - 0.5 * (phh - 1.0), 0.0, im_h - 1.0)
    x2 = jnp.clip(px + 0.5 * (pw - 1.0), 0.0, im_w - 1.0)
    y2 = jnp.clip(py + 0.5 * (phh - 1.0), 0.0, im_h - 1.0)
    boxes = jnp.stack([x1, y1, x2, y2], axis=1)

    # padded region (beyond real feature extent) + min_size filter
    hw = jnp.arange(H * W * K) // K
    hh, ww = hw // W, hw % W
    real_h = (im_h / stride).astype(jnp.int32)
    real_w = (im_w / stride).astype(jnp.int32)
    padded = (hh >= real_h) | (ww >= real_w)
    ms = min_size * im_scale
    small = ((x2 - x1 + 1.0) < ms) | ((y2 - y1 + 1.0) < ms)
    sc = jnp.where(padded | small, -1.0, scores)

    # top-pre_n by score (stable descending)
    order = jnp.argsort(-sc, stable=True)
    n_total = boxes.shape[0]
    pre = min(pre_n, n_total) if pre_n > 0 else n_total
    sel = order[:pre]
    b = boxes[sel]
    s = sc[sel]

    # NMS with +1 pixel areas (ref: proposal.cc NonMaximumSuppression)
    tl = jnp.maximum(b[:, None, :2], b[None, :, :2])
    br = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl + 1.0, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area = (b[:, 2] - b[:, 0] + 1.0) * (b[:, 3] - b[:, 1] + 1.0)
    iou = inter / (area[:, None] + area[None, :] - inter)
    rank = jnp.arange(pre)
    sup = (iou >= thresh) & (rank[None, :] > rank[:, None])

    def body(i, alive):
        return jnp.where(alive[i], alive & ~sup[i], alive)

    alive = lax.fori_loop(0, pre, body, jnp.ones((pre,), bool))

    # keep first post_n alive rows; pad by cycling (ref behaviour:
    # out[i] = keep[i % out_size])
    keep_rank = jnp.cumsum(alive.astype(jnp.int32)) - 1  # rank among kept
    out_size = jnp.maximum(alive.sum(), 1)
    # kept[j] = index of j-th alive row
    kept = jnp.full((pre,), 0, jnp.int32)
    kept = kept.at[jnp.where(alive, keep_rank, pre - 1)].set(
        jnp.arange(pre, dtype=jnp.int32), mode="drop")
    idx = kept[jnp.arange(post_n) % out_size]
    rois = jnp.concatenate(
        [jnp.zeros((post_n, 1), jnp.float32), b[idx]], axis=1)
    return rois, s[idx][:, None]


@defop("_contrib_Proposal", cache_vjp=True, num_outputs=lambda p:
       2 if p.get("output_score", False) else 1, differentiable=False)
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal generation (ref: src/operator/contrib/proposal.cc;
    batch must be 1 like the reference).  cls_prob (1, 2K, H, W),
    bbox_pred (1, 4K, H, W), im_info (1, 3) ->
    rois (post_n, 5) [+ scores (post_n, 1)]."""
    assert not iou_loss, "iou_loss=True path not implemented"
    scales = _tuple(scales)
    ratios = _tuple(ratios)
    K = cls_prob.shape[1] // 2
    base = _gen_base_anchors(float(feature_stride), scales, ratios)
    fg = cls_prob[0, K:].astype(jnp.float32)
    rois, sc = _proposal_one(fg, bbox_pred[0].astype(jnp.float32),
                             im_info[0].astype(jnp.float32), base,
                             float(feature_stride),
                             int(rpn_pre_nms_top_n),
                             int(rpn_post_nms_top_n), float(threshold),
                             float(rpn_min_size))
    rois = rois.astype(cls_prob.dtype)
    if output_score:
        return rois, sc.astype(cls_prob.dtype)
    return rois


@defop("_contrib_MultiProposal", cache_vjp=True, num_outputs=lambda p:
       2 if p.get("output_score", False) else 1, differentiable=False)
def multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                   rpn_post_nms_top_n=300, threshold=0.7,
                   rpn_min_size=16, scales=(4.0, 8.0, 16.0, 32.0),
                   ratios=(0.5, 1.0, 2.0), feature_stride=16,
                   output_score=False, iou_loss=False):
    """Batched Proposal (ref: src/operator/contrib/multi_proposal-inl.h)
    -> rois (B*post_n, 5) with per-image batch indices."""
    assert not iou_loss, "iou_loss=True path not implemented"
    scales = _tuple(scales)
    ratios = _tuple(ratios)
    B = cls_prob.shape[0]
    K = cls_prob.shape[1] // 2
    base = _gen_base_anchors(float(feature_stride), scales, ratios)

    rois, scs = jax.vmap(
        lambda cp, bp, ii: _proposal_one(
            cp[K:].astype(jnp.float32), bp.astype(jnp.float32),
            ii.astype(jnp.float32), base, float(feature_stride),
            int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n),
            float(threshold), float(rpn_min_size)))(
        cls_prob, bbox_pred, im_info)
    # stamp per-image batch index into column 0
    bidx = jnp.repeat(jnp.arange(B, dtype=jnp.float32),
                      rois.shape[1])[:, None]
    rois = rois.reshape(B * rois.shape[1], 5)
    rois = jnp.concatenate([bidx, rois[:, 1:]], axis=1)
    rois = rois.astype(cls_prob.dtype)
    if output_score:
        return rois, scs.reshape(-1, 1).astype(cls_prob.dtype)
    return rois


# ---------------------------------------------------------------------------
# DeformableConvolution
# ---------------------------------------------------------------------------

def _bilinear_sample(img, y, x):
    """img (H, W); y, x arbitrary same-shape float coords; zero
    outside [0, H) x [0, W) (ref: deformable_im2col.cuh
    deformable_im2col_bilinear + boundary guard)."""
    H, W = img.shape
    inb = (y >= 0) & (x >= 0) & (y < H) & (x < W)
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly, lx = y - y0, x - x0
    v = (img[y0, x0] * (1 - ly) * (1 - lx)
         + img[y0, x1] * (1 - ly) * lx
         + img[y1, x0] * ly * (1 - lx)
         + img[y1, x1] * ly * lx)
    return jnp.where(inb, v, 0.0)


@defop("_contrib_DeformableConvolution", variadic=True)
def deformable_convolution(*inputs, kernel=(3, 3), stride=(1, 1),
                           dilate=(1, 1), pad=(0, 0), num_filter=1,
                           num_group=1, num_deformable_group=1,
                           workspace=1024, no_bias=False, layout=None):
    """Deformable convolution v1 (ref:
    src/operator/contrib/deformable_convolution-inl.h): bilinear
    im2col at offset-shifted taps, then one grouped MXU matmul.
    inputs: data (B, C, H, W), offset (B, 2*K*K*dg, H', W'),
    weight (O, C/g, kh, kw)[, bias (O,)]."""
    data, offset, weight = inputs[0], inputs[1], inputs[2]
    bias = None if no_bias or len(inputs) < 4 else inputs[3]
    kh, kw = _tuple(kernel, 2, int)
    sh, sw = _tuple(stride, 2, int)
    dh, dw = _tuple(dilate, 2, int)
    ph, pw = _tuple(pad, 2, int)
    B, C, H, W = data.shape
    O = int(num_filter)
    G = int(num_group)
    DG = int(num_deformable_group)
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    cpg = C // DG                                   # chans / deform group

    # sampling coordinates per (dg, kh*kw, Ho, Wo)
    base_y = (jnp.arange(Ho) * sh - ph)[:, None] \
        + (jnp.arange(kh) * dh)[None, :]            # (Ho, kh)
    base_x = (jnp.arange(Wo) * sw - pw)[:, None] \
        + (jnp.arange(kw) * dw)[None, :]            # (Wo, kw)

    off = offset.reshape(B, DG, kh * kw, 2, Ho, Wo)
    oy = off[:, :, :, 0]                            # (B, DG, K2, Ho, Wo)
    ox = off[:, :, :, 1]
    # absolute sampling coordinates (K2, Ho, Wo) + learned offsets
    gy = jnp.broadcast_to(base_y.T[:, None, :, None], (kh, kw, Ho, Wo))
    gx = jnp.broadcast_to(base_x.T[None, :, None, :], (kh, kw, Ho, Wo))
    gy = gy.reshape(kh * kw, Ho, Wo)[None, None] + oy  # (B,DG,K2,Ho,Wo)
    gx = gx.reshape(kh * kw, Ho, Wo)[None, None] + ox

    def per_image(x, ys, xs):                       # x (C,H,W)
        xg = x.reshape(DG, cpg, H, W)
        # channels within a deformable group share their coordinates
        cols = jax.vmap(lambda grp, yg, xg_:
                        jax.vmap(lambda img: _bilinear_sample(
                            img, yg, xg_))(grp))(xg, ys, xs)
        return cols.reshape(C, kh * kw, Ho, Wo)

    cols = jax.vmap(per_image)(data.astype(jnp.float32), gy, gx)
    # cols: (B, C, K2, Ho, Wo) -> grouped matmul with weight
    wmat = weight.reshape(G, O // G, (C // G) * kh * kw) \
        .astype(jnp.float32)
    cols = cols.reshape(B, G, (C // G) * kh * kw, Ho * Wo)
    out = jnp.einsum("gok,bgkp->bgop", wmat, cols) \
        .reshape(B, O, Ho, Wo)
    if bias is not None:
        out = out + bias.astype(jnp.float32)[None, :, None, None]
    return out.astype(data.dtype)
