"""Per-op argument-shape inference hooks.

Role analog of the reference's FInferShape attrs (ref:
src/executor/infer_graph_attr_pass.cc fixed-point inference): given
the *known* input shapes of a node, fill in the shapes of its
parameter/aux inputs so `simple_bind(data=(N, ...))` can allocate
every weight without the user spelling them out.

Output shapes never need hooks — once all inputs are known,
jax.eval_shape gives exact outputs for free.
"""
from .registry import OPS


def _prod(t):
    out = 1
    for v in t:
        out *= v
    return out


def _tup(v, n, default):
    if v is None or v == ():
        return (default,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    return t if len(t) == n else t + (default,) * (n - len(t))


HOOKS = {}


def hook(name):
    def _reg(fn):
        HOOKS[name] = fn
        for alias_name, op in OPS.items():
            if op is OPS.get(name) and alias_name != name:
                HOOKS[alias_name] = fn
        return fn
    return _reg


@hook("FullyConnected")
def _fc(shapes, params):
    data = shapes[0]
    if data is None:
        return shapes
    nh = int(params.get("num_hidden", 0))
    ind = _prod(data[1:]) if params.get("flatten", True) else data[-1]
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (nh, ind)
    if len(out) > 2 and out[2] is None:
        out[2] = (nh,)
    return out


@hook("Convolution")
def _conv(shapes, params):
    data = shapes[0]
    if data is None:
        return shapes
    nf = int(params.get("num_filter", 0))
    ng = int(params.get("num_group", 1))
    k = tuple(int(x) for x in params.get("kernel", ()))
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (nf, data[1] // ng) + k
    if len(out) > 2 and out[2] is None:
        out[2] = (nf,)
    return out


@hook("Deconvolution")
def _deconv(shapes, params):
    data = shapes[0]
    if data is None:
        return shapes
    nf = int(params.get("num_filter", 0))
    ng = int(params.get("num_group", 1))
    k = tuple(int(x) for x in params.get("kernel", ()))
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (data[1], nf // ng) + k
    if len(out) > 2 and out[2] is None:
        out[2] = (nf,)
    return out


def _channel_params(n_param):
    def _h(shapes, params):
        data = shapes[0]
        if data is None:
            return shapes
        ax = int(params.get("axis", 1)) % len(data)
        c = data[ax]
        out = list(shapes)
        for i in range(1, min(len(out), 1 + n_param)):
            if out[i] is None:
                out[i] = (c,)
        return out
    return _h


HOOKS["BatchNorm"] = _channel_params(4)
HOOKS["BatchNorm_v1"] = _channel_params(4)
HOOKS["CuDNNBatchNorm"] = _channel_params(4)
HOOKS["InstanceNorm"] = _channel_params(2)


def _layernorm(shapes, params):
    data = shapes[0]
    if data is None:
        return shapes
    ax = int(params.get("axis", -1)) % len(data)
    c = data[ax]
    out = list(shapes)
    for i in (1, 2):
        if i < len(out) and out[i] is None:
            out[i] = (c,)
    return out


HOOKS["LayerNorm"] = _layernorm


@hook("Embedding")
def _embed(shapes, params):
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (int(params.get("input_dim", 0)),
                  int(params.get("output_dim", 0)))
    return out


def _prelu(shapes, params):
    if params.get("act_type") != "prelu" or shapes[0] is None:
        return shapes
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (shapes[0][1],)
    return out


HOOKS["LeakyReLU"] = _prelu
