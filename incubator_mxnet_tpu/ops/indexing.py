"""Indexing ops (ref: src/operator/tensor/indexing_op.cc): Embedding,
take, batch_take, one_hot, pick, gather_nd, scatter_nd.

On TPU these lower to XLA gather/scatter HLOs (the reference needed
CUB kernels; XLA emits them natively).
"""
import jax.numpy as jnp

from .registry import defop


@defop("Embedding", aliases=["_contrib_SparseEmbedding"])
def embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    """Row lookup into an (input_dim, output_dim) table."""
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@defop("take")
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[int(axis)])
    return jnp.take(a, idx, axis=int(axis), mode="clip")


@defop("batch_take")
def batch_take(a, indices):
    """a[i, indices[i]] (ref: indexing_op.cc batch_take)."""
    idx = indices.astype(jnp.int32).reshape(-1)
    return a[jnp.arange(a.shape[0]), idx]


@defop("one_hot", differentiable=False)
def one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import np_dtype
    idx = indices.astype(jnp.int32)
    eye = jnp.arange(int(depth), dtype=jnp.int32)
    out = jnp.where(idx[..., None] == eye, on_value, off_value)
    return out.astype(np_dtype(dtype))


@defop("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    ax = int(axis) % data.ndim
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[ax] - 1)
    picked = jnp.take_along_axis(data, jnp.expand_dims(idx, ax), axis=ax)
    return picked if keepdims else jnp.squeeze(picked, ax)


@defop("gather_nd")
def gather_nd(data, indices):
    """indices shape (M, ...) indexes the first M dims of data."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@defop("scatter_nd")
def scatter_nd(data, indices, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(int(s) for s in shape),
                    dtype=jnp.result_type(data))
    return out.at[tuple(idx[i] for i in range(m))].add(data)


@defop("_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(rhs)
