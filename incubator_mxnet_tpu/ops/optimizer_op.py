"""Fused optimizer update ops (ref: src/operator/optimizer_op.cc —
sgd_update:39, sgd_mom_update:66, mp_sgd_update:111, adam_update:146,
rmsprop_update:195, rmspropalex_update:245, ftrl_update:286).

Each is one fused XLA region; under jit the whole parameter update of
a model becomes a single executable (the reference needed hand-fused
CUDA kernels for this).  All are registered as ops so the Python
Optimizer classes stay thin dispatchers, exactly like the reference.
"""
import jax.numpy as jnp

from .registry import defop


def _rescale_clip(grad, rescale_grad, clip_gradient, wd=0.0, weight=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd and weight is not None:
        g = g + wd * weight
    return g


@defop("sgd_update", differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g


@defop("sgd_mom_update", differentiable=False, num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mom_new = momentum * mom - lr * g
    return weight + mom_new, mom_new


@defop("mp_sgd_update", differentiable=False, num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0):
    """Multi-precision SGD: fp32 master weights for bf16/fp16 params."""
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad,
                      clip_gradient, wd, weight32)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@defop("mp_sgd_mom_update", differentiable=False, num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad,
                      clip_gradient, wd, weight32)
    mom_new = momentum * mom - lr * g
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


@defop("adam_update", differentiable=False, num_outputs=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * m / (jnp.sqrt(v) + epsilon)
    return w, m, v


@defop("rmsprop_update", differentiable=False, num_outputs=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new


@defop("rmspropalex_update", differentiable=False, num_outputs=4)
def rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    gr = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    n_new = (1 - gamma1) * jnp.square(gr) + gamma1 * n
    g_new = (1 - gamma1) * gr + gamma1 * g
    delta_new = (gamma2 * delta
                 - lr * gr / jnp.sqrt(n_new - jnp.square(g_new) + epsilon))
    w = weight + delta_new
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new, g_new, delta_new


@defop("ftrl_update", differentiable=False, num_outputs=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z_new) <= lamda1, jnp.zeros_like(weight),
        -(z_new - jnp.sign(z_new) * lamda1)
        / ((beta + jnp.sqrt(n_new)) / lr + wd))
    return w, z_new, n_new


@defop("signsgd_update", differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * jnp.sign(g)


@defop("signum_update", differentiable=False, num_outputs=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mom_new = momentum * mom - (1 - momentum) * g
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return w, mom_new
