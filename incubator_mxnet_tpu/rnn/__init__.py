"""Legacy mx.rnn API (ref: python/mxnet/rnn/): symbolic-era RNN cells
and the bucketed data iterator.  The cell classes re-export gluon's
(the reference kept two parallel hierarchies; one is enough here —
same math, same parameter names)."""
from ..gluon.rnn.rnn_cell import (RecurrentCell, RNNCell, LSTMCell,
                                  GRUCell, SequentialRNNCell,
                                  DropoutCell, ModifierCell,
                                  ZoneoutCell, ResidualCell,
                                  BidirectionalCell)
from .io import BucketSentenceIter, encode_sentences

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell",
           "BucketSentenceIter", "encode_sentences"]
