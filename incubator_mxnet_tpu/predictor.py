"""Deployment predict API.

Python analog of the reference's C predict ABI (ref:
include/mxnet/c_predict_api.h — MXPredCreate:87, MXPredSetInput:177,
MXPredForward:191, MXPredGetOutput:160, MXPredReshape) serving a
`HybridBlock.export` / `Module.save_checkpoint` artifact: symbol JSON
plus an arg:/aux: params file.  The whole graph compiles to one XLA
executable on first forward (shape-keyed jit cache), so repeat
predictions are a single device call.
"""
import re

import numpy as np

from . import symbol as sym_mod
from .context import default_context
from .ndarray import ndarray as nd_mod
from .ndarray.ndarray import NDArray

__all__ = ["Predictor", "load_params", "serve"]


def load_params(param_file):
    """Split an exported params file into (arg_params, aux_params) —
    same tag semantics as model.load_checkpoint (untagged keys count
    as args, unknown tags are ignored)."""
    from .model import split_tagged_params
    return split_tagged_params(nd_mod.load(param_file))


def _strip_scope(name):
    """Drop one leading gluon name-scope prefix ('transformerlm0_'
    etc.) so params saved from one model instance load into another
    instance of the same architecture (whose auto-prefix counter
    differs)."""
    return re.sub(r"^[a-z]+\d+_(?=.)", "", name, count=1)


def _load_block_params(model, arg_params):
    """Load a saved param dict into a gluon Block by exact name,
    falling back to scope-prefix-stripped matching."""
    params = model.collect_params()
    by_suffix = {}
    for k in arg_params:
        by_suffix.setdefault(_strip_scope(k), k)
    missing = []
    for name, p in params.items():
        src = arg_params.get(name)
        if src is None:
            src = arg_params.get(by_suffix.get(_strip_scope(name)))
        if src is None:
            missing.append(name)
            continue
        p.set_data(src)
    if missing:
        raise IOError(
            f"parameters missing from the artifact: {missing} "
            f"(artifact keys: {sorted(arg_params)[:8]}...)")


def serve(param_file, model, **engine_kwargs):
    """Serving engine over an exported/checkpointed LM artifact.

    Loads ``param_file`` (saved via ``model.collect_params().save``
    or a checkpoint's ``arg:``-tagged params) into ``model`` — a
    ``TransformerLM`` instance matching the saved architecture — and
    returns a :class:`~incubator_mxnet_tpu.serving.ServingEngine`
    over it (continuous batching + paged KV cache;
    docs/serving.md).  Engine kwargs (``max_batch``, ``quantize``,
    ...) pass through."""
    from .serving import ServingEngine
    arg_params, _aux = load_params(param_file)
    _load_block_params(model, arg_params)
    return ServingEngine(model, **engine_kwargs)


class Predictor:
    """Inference-only executor over an exported model.

    Parameters
    ----------
    symbol : path to ``*-symbol.json``, a JSON string, or a Symbol
    param_file : path to the ``*.params`` file (arg:/aux: keys)
    input_shapes : dict input name -> shape (incl. batch dim) — the
        reference's MXPredCreate input_keys/input_shape_* arrays
    ctx : Context (default: the default device)
    """

    def __init__(self, symbol, param_file, input_shapes, ctx=None,
                 type_dict=None):
        if isinstance(symbol, sym_mod.Symbol):
            self._symbol = symbol
        elif str(symbol).lstrip().startswith("{"):
            self._symbol = sym_mod.load_json(symbol)
        else:
            self._symbol = sym_mod.load(symbol)
        self._ctx = ctx or default_context()
        self._param_file = param_file
        arg_params, aux_params = load_params(param_file)
        shapes = dict(input_shapes)
        shapes.update({k: v.shape for k, v in arg_params.items()})
        # bind at the dtypes the model was trained/exported at (e.g.
        # bf16), not a silent float32 default; explicit type_dict wins
        td = {k: v.dtype
              for p in (arg_params, aux_params) for k, v in p.items()}
        td.update(type_dict or {})
        self._exec = self._symbol.simple_bind(
            self._ctx, grad_req="null", type_dict=td, **shapes)
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=True)
        # positional predict() order = the caller's input_shapes
        # declaration order (dict order), NOT graph-topological order
        args = set(self._symbol.list_arguments())
        self._input_names = [n for n in input_shapes if n in args]
        self._inputs = {}
        self._outputs = None

    # ---------------------------------------------------------- C-api
    def set_input(self, name, value):
        """MXPredSetInput analog."""
        if name not in self._input_names:
            raise KeyError(
                f"'{name}' is not an input (inputs: "
                f"{self._input_names})")
        self._inputs[name] = value if isinstance(value, NDArray) \
            else nd_mod.array(np.asarray(value))

    @property
    def graph_report(self):
        """Graph-optimization report of the serving bind (per-pass
        node deltas; docs/graph_passes.md)."""
        return self._exec.graph_report

    def forward(self, **inputs):
        """MXPredForward analog; inputs may also be passed directly."""
        for k, v in inputs.items():
            self.set_input(k, v)
        missing = [n for n in self._input_names
                   if n not in self._inputs]
        if missing:
            raise ValueError(f"inputs not set: {missing}")
        self._outputs = self._exec.forward(is_train=False,
                                           **self._inputs)
        return self._outputs

    def get_output(self, index=0):
        """MXPredGetOutput analog."""
        if self._outputs is None:
            raise RuntimeError("call forward() first")
        return self._outputs[index]

    def predict(self, *arrays):
        """Convenience: positional inputs -> first output's numpy."""
        if len(arrays) != len(self._input_names):
            raise ValueError(
                f"expected {len(self._input_names)} inputs "
                f"({self._input_names}), got {len(arrays)}")
        self.forward(**dict(zip(self._input_names, arrays)))
        return self.get_output(0).asnumpy()

    def reshape(self, input_shapes):
        """MXPredReshape analog: rebind for new input shapes (dtypes
        and parameters carry over via Executor.reshape)."""
        p = Predictor.__new__(Predictor)
        p._symbol = self._symbol
        p._ctx = self._ctx
        p._param_file = self._param_file
        p._exec = self._exec.reshape(**input_shapes)
        p._input_names = list(self._input_names)
        p._inputs = {}
        p._outputs = None
        return p

    def serve(self, model, **engine_kwargs):
        """Serving engine over this predictor's artifact — see
        module-level :func:`serve` (continuous batching + paged KV
        cache over the exported weights)."""
        return serve(self._param_file, model, **engine_kwargs)
