"""SequentialModule: chain modules head-to-tail (ref:
python/mxnet/module/sequential_module.py SequentialModule:28).

Each sub-module's outputs feed the next one's data inputs; backward
runs the chain in reverse, handing each module's input gradients to
its predecessor as output gradients.  The last module owns the
labels/loss.  The TPU caveat is latency, not correctness: each
sub-module is its own compiled executable, so a K-stage chain pays K
dispatches per step — single-symbol Module fuses into one; use this
when stages genuinely need separate binding (e.g. mixed grad_req or
staged freezing).
"""
import logging

from .base_module import BaseModule


class SequentialModule(BaseModule):
    """(ref: sequential_module.py:28)"""

    META_TAKE_LABELS = "take_labels"

    def __init__(self, logger=logging):
        super().__init__(logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None

    def add(self, module, **kwargs):
        """Append a sub-module.  ``take_labels=True`` marks the one
        fed the labels (normally the last, with the loss)."""
        self._modules.append(module)
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # ------------------------------------------------------------ names
    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    # ------------------------------------------------------------ params
    def get_params(self):
        arg, aux = {}, {}
        for m in self._modules:
            a, x = m.get_params()
            dup = (set(arg) & set(a)) | (set(aux) & set(x))
            if dup:
                raise ValueError(
                    f"duplicate parameter names across sub-modules: "
                    f"{sorted(dup)}; give stages distinct layer "
                    "names")
            arg.update(a)
            aux.update(x)
        return arg, aux

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        for m in self._modules:
            # each sub-module sees the other stages' keys as extras,
            # so allow_extra is forced; missing-key strictness is the
            # caller's choice and passes through
            m.init_params(initializer=initializer,
                          arg_params=arg_params,
                          aux_params=aux_params,
                          allow_missing=allow_missing,
                          force_init=force_init, allow_extra=True)
        self.params_initialized = True

    # ------------------------------------------------------------ bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert self._modules, "add() sub-modules before bind()"
        self._label_shapes = label_shapes
        shapes = list(data_shapes)
        n = len(self._modules)
        from ..io.io import DataDesc
        for i, (m, meta) in enumerate(zip(self._modules, self._metas)):
            takes_labels = meta.get(self.META_TAKE_LABELS,
                                    i == n - 1)
            # every module but the first needs grads flowing back in
            m.bind(shapes,
                   label_shapes=label_shapes if takes_labels else None,
                   for_training=for_training,
                   inputs_need_grad=inputs_need_grad or i > 0,
                   force_rebind=force_rebind, grad_req=grad_req)
            if i + 1 < n:
                # wire this module's outputs to the next one's data
                next_names = self._modules[i + 1].data_names
                shapes = [DataDesc(nn, tuple(os[1]))
                          for nn, os in zip(next_names,
                                            m.output_shapes)]
        self.binded = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        for m in self._modules:
            m.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                             optimizer_params=optimizer_params,
                             force_init=force_init)
        self.optimizer_initialized = True

    # ------------------------------------------------------------ step
    def forward(self, data_batch, is_train=None):
        from ..io.io import DataBatch
        batch = data_batch
        n = len(self._modules)
        for i, m in enumerate(self._modules):
            m.forward(batch, is_train=is_train)
            if i + 1 == n:
                break
            batch = DataBatch(m.get_outputs(),
                              data_batch.label, pad=data_batch.pad)

    def backward(self, out_grads=None):
        grads = out_grads
        for i in range(len(self._modules) - 1, -1, -1):
            self._modules[i].backward(out_grads=grads)
            if i > 0:
                grads = self._modules[i].get_input_grads()

    def update(self):
        for m in self._modules:
            m.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        n = len(self._modules)
        for i, (m, meta) in enumerate(zip(self._modules, self._metas)):
            if meta.get(self.META_TAKE_LABELS, i == n - 1):
                m.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for m in self._modules:
            m.install_monitor(mon)
