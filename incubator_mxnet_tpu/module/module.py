"""Module: concrete symbolic trainer over one compiled Executor
(ref: python/mxnet/module/module.py:  bind:355, init_params,
init_optimizer:464, forward:560, backward:602, update:619,
update_metric:726).

TPU-native note: the reference slices each batch across GPUs with
DataParallelExecutorGroup (ref: executor_group.py:99); here a single
Executor compiles the whole graph and data parallelism is expressed
with sharded batch arrays over the device mesh (parallel package), so
the "group" collapses to one executor whose inputs may be sharded.
"""
import logging

from .. import initializer as init_mod
from .. import optimizer as opt_mod
from .. import telemetry
from ..initializer import InitDesc
from ..model import (_create_kvstore, save_checkpoint,
                     load_checkpoint, checkpoint_companion_path,
                     save_data_state, load_data_state)
from ..ndarray.ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._context = context
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._guard = None       # step sentinel (MXTPU_NONFINITE_POLICY)
        self._kvstore = None
        self._update_on_kvstore = False
        self._data_shapes = None
        self._label_shapes = None
        self._mesh_step = None   # kvstore='tpu' fused path
        self._mesh_dirty = False    # step params newer than exec dicts
        self._mesh_pending = False  # fused step ran; update() owes a no-op
        self._mesh_stale = False    # exec dicts newer than step params
        self._perf_clock = None     # MFU gauges (perf observatory)
        self._perf_cost = None      # cached graph CostReport (3x fwd)
        self._perf_tried = False    # don't re-cost after a failure

    # ------------------------------------------------------------ bind
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return list(zip(self.output_names, self._exec.output_shapes))

    @property
    def graph_opt_report(self):
        """Pass-pipeline report of the bound executor (per-pass node
        deltas; docs/graph_passes.md).  None before bind."""
        return getattr(self, "_graph_opt_report", None)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = [d if hasattr(d, "name") else
                             _to_desc(d) for d in data_shapes]
        self._label_shapes = [d if hasattr(d, "name") else _to_desc(d)
                              for d in (label_shapes or [])]
        shapes = {d.name: d.shape for d in self._data_shapes}
        shapes.update({d.name: d.shape for d in self._label_shapes})
        if isinstance(grad_req, str):
            req = {}
            for n in self._symbol.list_arguments():
                if n in self._fixed_param_names or (
                        not for_training) or (
                        n in self._data_names and not inputs_need_grad
                ) or n in self._label_names:
                    req[n] = "null"
                else:
                    req[n] = grad_req
        else:
            req = grad_req
        self._preflight_memory(shapes, for_training)
        self._exec = self._symbol.simple_bind(
            self._context, grad_req=req, **shapes)
        # pass-pipeline outcome of this bind (docs/graph_passes.md):
        # per-pass node deltas, None when MXTPU_GRAPH_OPT=0 or placed
        self._graph_opt_report = self._exec.graph_report
        if shared_module is not None and shared_module._exec is not None:
            self._exec.copy_params_from(
                shared_module._exec.arg_dict,
                shared_module._exec.aux_dict, allow_extra_params=True)
        self.binded = True
        # Module.load path: apply checkpointed params on first bind
        # (ref: module.py Module.load sets _arg_params + initialized)
        if getattr(self, "_preloaded_params", None) is not None:
            arg, aux = self._preloaded_params
            self.init_params(arg_params=arg, aux_params=aux,
                             force_init=True)
            self._preloaded_params = None

    def _preflight_memory(self, shapes, for_training):
        """Analytic HBM gate at bind time (docs/memory.md): plan the
        executor's peak live bytes (eager grads, no donation) against
        device capacity per MXTPU_MEM_POLICY.  The single-executor
        path has no remat/grad_accum rungs, so the ladder is empty —
        the plan fits, warns, or raises a typed MemoryPlanError
        before any compile.  Planner failures on exotic graphs are
        non-fatal; the gate is a guard, not a dependency."""
        from ..perf import memory_planner as mp
        from ..resilience import MemoryPlanError
        try:
            live = mp.symbol_liveness(self._symbol, dict(shapes),
                                      input_names=list(shapes))
            mp.preflight(
                lambda r, a: mp.plan_memory(
                    liveness=live, train=for_training,
                    donate=False, grad_accum=a, remat=r),
                site="module_bind")
        except MemoryPlanError:
            raise
        except Exception:
            self.logger.debug(
                "memory preflight skipped (planning failed)",
                exc_info=True)

    # ------------------------------------------------------------ params
    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        attrs = self._symbol.attr_dict()

        def _fill(name, arr, cache):
            if cache is not None and name in cache:
                arr[:] = cache[name]
                return
            if cache is not None and not allow_missing:
                raise RuntimeError(
                    f"parameter '{name}' missing from provided params "
                    "(pass allow_missing=True to initialize it)")
            if initializer is not None:
                initializer(InitDesc(name, attrs.get(name, {})), arr)
            elif cache is None:
                init_mod.Uniform(0.01)(
                    InitDesc(name, attrs.get(name, {})), arr)

        for name in self._param_names:
            _fill(name, self._exec.arg_dict[name], arg_params)
        for name in self._aux_names:
            _fill(name, self._exec.aux_dict[name], aux_params)
        if self._mesh_step is not None:
            # the exec dicts are now the source of truth (set_params
            # mid-training, divergence rollback): the mesh step must
            # re-pull them before its next fused step, and a pending
            # sync from the step must not clobber them
            self._mesh_dirty = False
            self._mesh_stale = True
        self.params_initialized = True

    def get_params(self):
        self._sync_mesh_params()
        arg = {n: self._exec.arg_dict[n].copy()
               for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy()
               for n in self._aux_names}
        return arg, aux

    # ------------------------------------------------------------ optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        arg_params = {n: self._exec.arg_dict[n]
                      for n in self._param_names}
        use_mesh_step = (isinstance(kvstore, str) and kvstore == "tpu")
        kv, update_on_kvstore = (None, False) if use_mesh_step else \
            _create_kvstore(kvstore, 1, arg_params)
        if isinstance(optimizer, str):
            params = dict(optimizer_params or ())
            # reference default: scale summed grads by 1/batch_size
            # (ref: module.py init_optimizer:464 rescale_grad); on a
            # multi-process mesh the global batch is num_workers larger
            if "rescale_grad" not in params and self._data_shapes:
                batch_size = self._data_shapes[0].shape[0]
                if kv is not None and kv.num_workers > 1:
                    batch_size *= kv.num_workers
                params["rescale_grad"] = 1.0 / max(batch_size, 1)
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer = opt_mod.create(
                optimizer, sym=self._symbol, param_idx2name=idx2name,
                **params)
        self._optimizer = optimizer
        self._kvstore = kv
        self._update_on_kvstore = update_on_kvstore and kv is not None
        self._updater = None
        # step sentinel (docs/numeric_stability.md): armed by
        # MXTPU_NONFINITE_POLICY; the Module path has no user-scaled
        # loss, so no LossScaler here (that is the gluon Trainer's)
        from ..resilience import NumericGuard
        guard = NumericGuard(name="Module")
        self._guard = guard if guard.enabled else None
        if use_mesh_step:
            self._init_mesh_step()
        if kv is not None:
            for i, name in enumerate(self._param_names):
                kv.init(i, self._exec.arg_dict[name])
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        if not self._update_on_kvstore and not use_mesh_step:
            self._updater = opt_mod.GuardedUpdater(
                optimizer, guard=self._guard) \
                if self._guard is not None \
                else opt_mod.get_updater(optimizer)
        if not use_mesh_step:
            # device-memory attribution (docs/observability.md); the
            # mesh path's SymbolTrainStep registers its own providers
            self._register_memory_providers()
        self.optimizer_initialized = True
        states = getattr(self, "_preload_opt_states", None)
        if states:
            from ..resilience import CheckpointCorruptError
            try:
                self.load_optimizer_states(states)
            except (FileNotFoundError, CheckpointCorruptError) as exc:
                # the params may have come from a fallback epoch
                # whose .states never existed or was torn; resume
                # with fresh optimizer state rather than crash:
                # weights are intact, momentum rebuilds.  Other
                # OSErrors (EACCES, transient NFS faults) stay loud —
                # the state likely exists and dropping it would
                # silently degrade convergence
                import warnings
                warnings.warn(
                    f"optimizer states {states} could not be loaded "
                    f"({exc}); resuming with freshly initialized "
                    "optimizer state", RuntimeWarning)
            self._preload_opt_states = None

    def _register_memory_providers(self):
        """Attribute this module's device buffers in the tracing
        layer's memory gauges: bound params + eager-updater optimizer
        state.  Weakref'd so a dropped module stops being counted;
        idempotent per init_optimizer (providers re-register on
        force_init, superseding via the old module's weakref dying
        with it)."""
        from .. import tracing
        for unreg in getattr(self, "_mem_unregister", ()):
            unreg()

        def _param_arrays(mod):
            if mod._exec is None:
                return []
            return [mod._exec.arg_dict[n]._data
                    for n in mod._param_names
                    if n in mod._exec.arg_dict]

        def _opt_arrays(mod):
            states = getattr(mod._updater, "states", None)
            return tracing.updater_state_arrays(states) \
                if states else []

        self._mem_unregister = tracing.register_param_opt_providers(
            self, _param_arrays, _opt_arrays)

    # ------------------------------------------------------------ mesh
    def _init_mesh_step(self):
        """kvstore='tpu': build the fused mesh training step.

        Replaces DataParallelExecutorGroup batch slicing + kvstore
        push/pull (ref: python/mxnet/module/executor_group.py:99) with
        one jit step over the ambient mesh: batch sharded on 'dp',
        grads psum'd by XLA, functional optimizer applied in-jit.
        """
        from ..parallel import current_mesh, make_mesh
        from ..parallel.symbol_step import SymbolTrainStep
        opt = self._optimizer
        fopt = _to_functional_optimizer(opt)
        if fopt is None:
            raise ValueError(
                f"kvstore='tpu' supports sgd/nag/adam-family "
                f"optimizers in the fused step; got "
                f"{type(opt).__name__}. Use kvstore='device' for the "
                "eager update path.")
        trainable = [n for n in self._param_names
                     if n in self._exec.grad_dict]
        pvals = {n: self._exec.arg_dict[n]._data for n in trainable}
        # fixed params + aux states ride in the aux (constant) slot
        aux_vals = {n: self._exec.aux_dict[n]._data
                    for n in self._aux_names}
        aux_vals.update({n: self._exec.arg_dict[n]._data
                         for n in self._param_names
                         if n not in self._exec.grad_dict})
        input_names = [d.name for d in self._data_shapes]
        input_names += [d.name for d in (self._label_shapes or [])
                        if d.name in self._exec.arg_dict]
        from ..parallel.optim import default_wd_mults
        wd_mults = default_wd_mults(trainable, opt.wd_mult)
        lr_mults = {n: opt.lr_mult.get(n, 1.0) for n in trainable}
        mesh = current_mesh() or make_mesh()
        self._mesh_step = SymbolTrainStep(
            self._symbol, pvals, aux_vals, input_names,
            optimizer=fopt, mesh=mesh,
            rescale_grad=getattr(opt, "rescale_grad", 1.0),
            lr_mults=lr_mults, wd_mults=wd_mults,
            numeric_guard=self._guard is not None,
            guard_select=self._guard is not None
            and self._guard.drops_updates)

    def _sync_mesh_params(self):
        """Pull owned copies from the mesh step back into the
        executor dicts (lazy: only when values are actually read)."""
        if self._mesh_step is None or not self._mesh_dirty:
            return
        params, aux = self._mesh_step.owned_values()
        for n, v in params.items():
            self._exec.arg_dict[n]._data = v
        for n, v in aux.items():
            if n in self._exec.aux_dict:
                self._exec.aux_dict[n]._data = v
            else:  # fixed params rode in the aux slot
                self._exec.arg_dict[n]._data = v
        self._mesh_dirty = False

    # ------------------------------------------------------------ step
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        inputs = self._batch_inputs(data_batch)
        if not is_train and self._mesh_step is not None \
                and not self._mesh_stale:
            vals = {k: (v._data if isinstance(v, NDArray) else v)
                    for k, v in inputs.items()}
            need = self._mesh_step.input_names
            dp = self._mesh_step.mesh.shape["dp"]
            batches = [vals[n].shape[0] for n in need if n in vals]
            if set(need) <= set(vals) and \
                    all(b % dp == 0 for b in batches):
                # compiled sharded eval over the mesh (score/predict)
                outs = self._mesh_step.evaluate(
                    {n: vals[n] for n in need})
                self._exec._outputs = [NDArray(o) for o in outs]
                return
        self._sync_mesh_params()
        self._exec.forward(is_train=is_train, **inputs)

    def _batch_inputs(self, data_batch):
        inputs = {}
        bound = self._exec.arg_dict
        for desc, arr in zip(self._data_shapes, data_batch.data):
            inputs[desc.name] = arr
        if data_batch.label is not None and self._label_shapes:
            for desc, arr in zip(self._label_shapes, data_batch.label):
                if desc.name in bound:  # symbol may be label-free
                    inputs[desc.name] = arr
        return inputs

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        """Fused single-XLA-call training step (outputs + grads)."""
        if self._mesh_step is not None:
            from ..parallel.optim import scheduled_lr
            if self._mesh_stale:
                # an eager update touched the exec dicts; refresh the
                # step's device values before continuing fused
                self._push_mesh_params()
            inputs = {k: v._data if isinstance(v, NDArray) else v
                      for k, v in self._batch_inputs(data_batch).items()}
            outs = self._mesh_step(inputs,
                                   lr=scheduled_lr(self._optimizer))
            self._exec._outputs = [NDArray(o) for o in outs]
            self._mesh_dirty = True
            self._mesh_pending = True
            return
        self._exec.forward_backward(**self._batch_inputs(data_batch))

    def _push_mesh_params(self):
        trainable = {n: self._exec.arg_dict[n]._data
                     for n in self._mesh_step.params}
        aux = {n: (self._exec.aux_dict[n]._data
                   if n in self._exec.aux_dict
                   else self._exec.arg_dict[n]._data)
               for n in self._mesh_step.aux}
        self._mesh_step.set_values(trainable, aux)
        self._mesh_stale = False

    def update(self):
        """(ref: module.py update:619 / model.py
        _update_params_on_kvstore:105)

        Step sentinel (docs/numeric_stability.md): with
        MXTPU_NONFINITE_POLICY armed, the step's gradients reduce to
        one fused finiteness scalar, host-read every
        MXTPU_GUARD_INTERVAL steps; a bad step is skipped whole
        (weights, optimizer state, LR-schedule count), and
        MXTPU_MAX_BAD_STEPS consecutive bad steps raise
        DivergedError for fit's checkpoint rollback."""
        assert self.optimizer_initialized
        telemetry.counter("train_steps_total").inc()
        # perf observatory: wall-clock-only MFU clock — the mesh
        # step ticks its own, so only the executor path ticks here
        if self._mesh_step is None:
            if self._perf_clock is None and not self._perf_tried:
                self._arm_perf_clock()
            if self._perf_clock is not None:
                self._perf_clock.tick()
        if self._mesh_step is not None:
            if self._mesh_pending:
                # the optimizer already ran inside the fused mesh
                # step; the guarded build protected params/state on
                # device (in-jit select) — the host only consumes
                # the flag on due steps for policy and divergence
                # accounting
                self._mesh_pending = False
                if self._guard is not None:
                    due = self._guard.begin_step()
                    opt_mod.accumulate_window(
                        self._guard, self._mesh_step.last_finite)
                    if due:
                        # the one guard-interval device->host read —
                        # the 'host_sync' slice of the step timeline
                        with telemetry.span("host_sync"):
                            bad = opt_mod.read_window_bad(
                                self._guard)
                        if bad and self._guard.drops_updates:
                            # those updates were dropped on device;
                            # keep the LR schedule in step with the
                            # weights (exact count, before record —
                            # policy=raise raises there)
                            self._optimizer.num_update -= bad
                        self._guard.record(bad == 0,
                                           dropped=max(bad, 1))
                return
            # manual forward/backward loop with kvstore='tpu': apply
            # the eager updater so update() is never a silent no-op
            if self._updater is None:
                self._updater = opt_mod.GuardedUpdater(
                    self._optimizer, guard=self._guard) \
                    if self._guard is not None \
                    else opt_mod.get_updater(self._optimizer)
            self._sync_mesh_params()
            self._mesh_stale = True
        if self._guard is not None:
            grads = [g for g in
                     (self._exec.grad_dict.get(n)
                      for n in self._param_names) if g is not None]
            if isinstance(self._updater, opt_mod.GuardedUpdater):
                proceed = self._updater.begin_step(grads)
            else:
                # update_on_kvstore: the optimizer runs inside the
                # kvstore, so guard the step before any push — the
                # skip must also cover the collectives (rank-
                # consistent via the allreduced flag)
                proceed = opt_mod.guarded_step_begin(
                    self._guard, None, grads)
            if not proceed:
                return
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:  # fixed / grad_req=null parameters
                continue
            kv = self._kvstore
            if kv is not None and self._update_on_kvstore:
                kv.push(i, grad, priority=-i)
                kv.pull(i, out=self._exec.arg_dict[name], priority=-i)
            elif kv is not None:
                kv.push(i, grad, priority=-i)
                kv.pull(i, out=grad, priority=-i)
                self._updater(i, grad, self._exec.arg_dict[name])
            else:
                self._updater(i, grad, self._exec.arg_dict[name])

    # ------------------------------------------------------------ perf
    def _bound_shapes(self):
        """Variable name -> shape for everything the bind fixed."""
        shapes = {d.name: tuple(d.shape) for d in self._data_shapes}
        shapes.update({d.name: tuple(d.shape)
                       for d in (self._label_shapes or [])})
        for n in self._param_names:
            shapes[n] = tuple(self._exec.arg_dict[n].shape)
        for n in self._aux_names:
            shapes[n] = tuple(self._exec.aux_dict[n].shape)
        return shapes

    def _graph_cost(self):
        """Analytic CostReport of one TRAIN step (3x forward) at the
        bound shapes; cached per bind."""
        if self._perf_cost is None:
            from .. import perf
            self._perf_cost = perf.symbol_cost(
                self._symbol, self._bound_shapes()).scaled(3.0)
        return self._perf_cost

    def _arm_perf_clock(self):
        """One-time arm of the train_mfu/train_mbu clock from the
        graph cost model (bind-time work; never re-tried on
        failure, never on the step path)."""
        self._perf_tried = True
        try:
            from .. import perf
            rep = self._graph_cost()
            self._perf_clock = perf.TrainPerfClock(rep.flops,
                                                   rep.bytes)
        except Exception:
            self._perf_clock = None

    def perf_report(self, xla_check=True):
        """Per-family cost/roofline report for the bound graph
        (docs/observability.md "Perf observatory").

        Returns a dict: ``per_family`` rows (flops%, bytes%,
        predicted-time%, bound-by label, arithmetic intensity),
        ``total`` summary, coverage counts, the device roofline
        verdict for one train step, and — when the backend reports
        ``cost_analysis()`` — the analytic-vs-XLA forward-FLOPs
        delta."""
        assert self.binded, "call bind before perf_report"
        import jax

        from .. import perf
        rep = self._graph_cost()
        dev = jax.devices()[0]
        caps = perf.caps_for(dev)
        dtype = str(next(iter(self._exec.arg_dict.values())).dtype) \
            if self._exec.arg_dict else "float32"
        out = {
            "per_family": rep.table(caps, dtype),
            "total": rep.summary(),
            "coverage": rep.coverage,
            "default_ops": rep.default_ops,
            "unknown_ops": rep.unknown_ops,
            "roofline": perf.roofline(rep.flops, rep.bytes, caps,
                                      dtype),
            "device": caps.as_dict(),
            "n_nodes": rep.n_nodes,
        }
        if xla_check:
            out["xla_check"] = self._xla_fwd_delta(rep)
        return out

    def _xla_fwd_delta(self, train_rep):
        """Analytic-vs-XLA forward FLOPs delta via the executor's
        compiled forward (AOT lowering; nothing executes).  None
        when the backend doesn't report cost_analysis()."""
        import jax

        from .. import perf
        try:
            fwd = self._exec._get_fwd(False)
            args = {n: jax.ShapeDtypeStruct(tuple(v.shape),
                                            v.dtype)
                    for n, v in self._exec.arg_dict.items()}
            auxs = {n: jax.ShapeDtypeStruct(tuple(v.shape),
                                            v.dtype)
                    for n, v in self._exec.aux_dict.items()}
            import numpy as np
            rng = jax.ShapeDtypeStruct((2,), np.dtype("uint32"))
            xc = perf.jit_cost(fwd, args, auxs, rng)
        except Exception:
            return None
        if not xc or not xc.get("flops"):
            return None
        analytic_fwd = train_rep.flops / 3.0
        return {"analytic_fwd_flops": analytic_fwd,
                "xla_fwd_flops": xc["flops"],
                "rel_delta": abs(analytic_fwd - xc["flops"])
                / xc["flops"]}

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self._exec.outputs)

    def install_monitor(self, mon):
        mon.install(self._exec)

    # ------------------------------------------------------------ ckpt
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        data_iter=None):
        """Save params (+ optimizer states, + input-pipeline position
        when ``data_iter`` is given) — every file atomically, so the
        launcher's restart loop always finds a coherent set."""
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")
        if data_iter is not None:
            save_data_state(prefix, epoch, data_iter)

    @staticmethod
    def load_data_state(prefix, epoch, data_iter, strict=False):
        """Restore ``data_iter`` from the checkpoint's ``.data``
        companion (see ``model.load_data_state``): the resumed stream
        continues at the exact batch the checkpoint was taken at."""
        return load_data_state(prefix, epoch, data_iter,
                               strict=strict)

    # ----------------------------------------------------- elastic ckpt
    def save_sharded_checkpoint(self, ckpt_dir, step=None,
                                data_iter=None):
        """Elastic sharded checkpoint (docs/elastic.md): params +
        aux + in-jit optimizer state land as one manifest generation
        under ``ckpt_dir``, each rank writing only the slices it
        owns; the input pipeline's position rides in the same
        generation when ``data_iter`` is given.  kvstore='tpu' mesh
        path only — the eager paths keep the legacy
        prefix/epoch format.  Returns the generation directory."""
        if self._mesh_step is None:
            raise RuntimeError(
                "save_sharded_checkpoint needs the kvstore='tpu' "
                "mesh step (legacy contexts: use save_checkpoint)")
        if self._mesh_stale:
            # an eager update / set_params touched the exec dicts
            # since the last fused step: checkpoint what the user
            # sees, not the step's pre-update device values
            self._push_mesh_params()
        data_state = data_iter.state_dict() \
            if data_iter is not None else None
        return self._mesh_step.save_checkpoint(
            ckpt_dir, step=step, data_state=data_state)

    def load_sharded_checkpoint(self, ckpt_dir, data_iter=None):
        """Restore the newest valid sharded generation into the mesh
        step — resharded onto THIS job's mesh, which need not match
        the saving job's shape or world size — and re-shard the data
        iterator's cursors from the generation's companion when
        ``data_iter`` is given.  Returns the companion state (or
        None)."""
        if self._mesh_step is None:
            raise RuntimeError(
                "load_sharded_checkpoint needs the kvstore='tpu' "
                "mesh step (legacy contexts: use model."
                "load_checkpoint)")
        state = self._mesh_step.load_checkpoint(ckpt_dir)
        # restored values are now the source of truth: exec dicts
        # must re-pull them, and no stale push may clobber them
        self._mesh_dirty = True
        self._mesh_stale = False
        if data_iter is not None and state is not None:
            data_iter.load_state_dict(state)
        return state

    def save_optimizer_states(self, fname):
        from .. import resilience
        assert self.optimizer_initialized
        if self._mesh_step is not None:
            import pickle
            import numpy as _np
            import jax as _jax
            tree = _jax.tree_util.tree_map(_np.asarray,
                                           self._mesh_step.opt_state)
            resilience.atomic_save(
                fname, lambda f: pickle.dump(tree, f))
        elif self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            resilience.atomic_write_bytes(
                fname, self._updater.get_states())

    def load_optimizer_states(self, fname):
        from .. import resilience
        assert self.optimizer_initialized
        if self._mesh_step is not None:
            import pickle
            import jax as _jax
            import jax.numpy as _jnp
            raw = resilience.read_validated_bytes(fname)
            tree = resilience.decode_or_corrupt(
                fname, lambda: pickle.loads(raw))
            self._mesh_step.opt_state = _jax.tree_util.tree_map(
                _jnp.asarray, tree)
        elif self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            import pickle
            raw = resilience.read_validated_bytes(fname)
            # decode under the corruption guard, apply outside it
            obj = resilience.decode_or_corrupt(
                fname, lambda: pickle.loads(raw))
            self._updater.set_states(obj)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Load a checkpointed Module; params apply automatically on
        bind() (ref: module.py Module.load)."""
        symbol, arg_params, aux_params, eff = load_checkpoint(
            prefix, epoch, return_epoch=True)
        mod = Module(symbol, **kwargs)
        mod._preloaded_params = (arg_params, aux_params)
        # pair optimizer state with the checkpoint that actually
        # loaded — a corrupt-load fallback may have substituted an
        # earlier one, possibly under an unpadded filename
        mod._preload_opt_states = \
            checkpoint_companion_path(prefix, eff) \
            if load_optimizer_states else None
        return mod


def _to_desc(d):
    from ..io.io import DataDesc
    name, shape = d
    return DataDesc(name, shape)


def _to_functional_optimizer(opt):
    from ..parallel.optim import from_imperative
    return from_imperative(opt)
