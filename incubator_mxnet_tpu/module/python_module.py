"""Python-computation modules (ref:
python/mxnet/module/python_module.py — PythonModule:28,
PythonLossModule:240).

PythonModule stubs the parameter/optimizer surface (a python module
owns no trainable parameters) so subclasses only implement
forward/backward; PythonLossModule is the common case — a hand-written
loss at the tail of a SequentialModule chain, computing input
gradients in python (or via a supplied ``grad_func``).
"""
import logging

import numpy as np

from .. import ndarray as nd
from ..io.io import DataDesc
from .base_module import BaseModule


class PythonModule(BaseModule):
    """Module whose computation is plain Python over NDArrays
    (ref: python_module.py:28)."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # ---------------------------------------------------------- names
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # ------------------------------------------------- param surface
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        self.optimizer_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        pass

    def install_monitor(self, mon):
        pass

    # ----------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = [
            d if isinstance(d, DataDesc) else DataDesc(*d)
            for d in data_shapes]
        self._label_shapes = None if label_shapes is None else [
            d if isinstance(d, DataDesc) else DataDesc(*d)
            for d in label_shapes]
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        """[(name, shape)] given self._data_shapes; default passes the
        first data shape through (override for anything else)."""
        return [(self._output_names[0],
                 tuple(self._data_shapes[0].shape))]


class PythonLossModule(PythonModule):
    """A python-computed loss head (ref: python_module.py:240).

    forward caches the input; get_outputs returns it unchanged (the
    'loss' is identity on the score for chaining); backward computes
    the input gradient via ``grad_func(label, pred) -> NDArray`` or a
    subclass override of ``_backward_impl``.
    """

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train and data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, \
            "PythonLossModule is a loss head; it takes no out_grads"
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        if self._grad_func is not None:
            grad = self._grad_func(self._labels, self._scores)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(np.asarray(grad))
            self._scores_grad = grad
        else:
            raise NotImplementedError(
                "pass grad_func or override _backward_impl")

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]

    def install_monitor(self, mon):
        pass
