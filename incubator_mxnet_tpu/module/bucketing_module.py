"""BucketingModule: variable-length training via per-bucket executors
(ref: python/mxnet/module/bucketing_module.py — switch_bucket:337 lazily
binds one Module per bucket sharing the default bucket's parameters;
memory sharing ref: src/executor/graph_executor.cc:918).

TPU-native note: each bucket is a distinct static shape, so each
bucket's Module compiles its own XLA executable — the signature-keyed
compile cache the reference's CachedOp/shared-executor machinery
approximates.  Parameters are synchronized into a bucket's module on
switch (the reference shares storage directly; here values are copied,
which XLA turns into cheap device-to-device aliasing)."""
import logging

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """Drives a ``sym_gen(bucket_key) -> (symbol, data_names,
    label_names)`` factory, one Module per bucket."""

    def __init__(self, sym_gen, default_bucket_key=None,
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None):
        super().__init__(logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._grad_req = "write"

    # ------------------------------------------------------------ props
    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        return self._curr_module._symbol

    # ------------------------------------------------------------ bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        """Bind the default bucket (ref: bucketing_module.py bind)."""
        if self.binded and not force_rebind:
            return
        assert shared_module is None, \
            "shared_module not supported for BucketingModule"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        symbol, data_names, label_names = self._sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names, label_names,
                        logger=self.logger, context=self._context,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes,
                      label_shapes=None):
        """Activate (lazily binding) the bucket's module (ref:
        bucketing_module.py switch_bucket:337)."""
        assert self.binded, "call bind before switch_bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, force_rebind=False,
                        shared_module=self._buckets[
                            self._default_bucket_key],
                        grad_req=self._grad_req)
            module.params_initialized = True
            if self.optimizer_initialized:
                self._borrow_optimizer(module)
            self._buckets[bucket_key] = module
        if bucket_key != self._curr_bucket_key:
            module = self._buckets[bucket_key]
            # sync params from the currently-active module
            if self._curr_module is not None and \
                    self._curr_module.params_initialized:
                arg, aux = self._curr_module.get_params()
                module._exec.copy_params_from(arg, aux,
                                              allow_extra_params=True)
            self._curr_module = module
            self._curr_bucket_key = bucket_key

    # ------------------------------------------------------------ params
    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._curr_module.init_params(initializer, arg_params,
                                      aux_params, allow_missing,
                                      force_init, allow_extra)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params,
                   allow_missing=False, force_init=True,
                   allow_extra=False):
        self._curr_module.init_params(
            arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init)
        self.params_initialized = True

    # ------------------------------------------------------------ optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Init on the default bucket; other buckets *borrow* the same
        optimizer/updater so momentum state stays continuous across
        bucket switches (ref: bucketing_module.py borrow_optimizer)."""
        if self.optimizer_initialized and not force_init:
            return
        default = self._buckets[self._default_bucket_key]
        default.init_optimizer(kvstore, optimizer, optimizer_params,
                               force_init)
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key:
                self._borrow_optimizer(mod)
        self.optimizer_initialized = True

    def _borrow_optimizer(self, module):
        """Share the default bucket's optimizer state (ref:
        module.py borrow_optimizer)."""
        default = self._buckets[self._default_bucket_key]
        module._optimizer = default._optimizer
        module._updater = default._updater
        module._kvstore = default._kvstore
        module._update_on_kvstore = default._update_on_kvstore
        module.optimizer_initialized = True

    # ------------------------------------------------------------ step
    def forward(self, data_batch, is_train=None):
        assert self.binded
        self.switch_bucket(data_batch.bucket_key,
                           data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)
