"""BaseModule: the high-level symbolic training interface
(ref: python/mxnet/module/base_module.py — fit:376, forward:754,
backward:792, update:876, bind:917, init_optimizer:958, score,
predict).
"""
import logging
import time

from .. import debugz
from .. import initializer as init_mod
from .. import metric as metric_mod
from .. import telemetry
from ..io.io import DataBatch
from ..model import BatchEndParam
from ..resilience import DivergedError

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.inputs_need_grad = False
        self._symbol = None

    # ------------------------------------------------------------ abstract
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    # ------------------------------------------------------------ helpers
    @property
    def symbol(self):
        return self._symbol

    def forward_backward(self, data_batch):
        """(ref: base_module.py:189)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(None, arg_params, aux_params, allow_missing,
                         force_init, allow_extra)

    # ------------------------------------------------------------ score
    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None,
              reset=True, epoch=0):
        """Evaluate on a data iterator (ref: base_module.py score)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            if pad:
                # wrap-padded duplicates must not count in the score
                outs = [o[:o.shape[0] - pad]
                        for o in self.get_outputs()]
                labels = [l[:l.shape[0] - pad]
                          for l in eval_batch.label]
                eval_metric.update(labels, outs)
            else:
                self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch, nbatch, eval_metric, locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        if score_end_callback is not None:
            param = BatchEndParam(epoch, nbatch, eval_metric, locals())
            for cb in _as_list(score_end_callback):
                cb(param)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            yield self.get_outputs(), nbatch, eval_batch

    def predict(self, eval_data, num_batch=None,
                merge_batches=True, reset=True, always_output_list=False):
        """(ref: base_module.py predict)"""
        from .. import nd
        assert self.binded and self.params_initialized
        if isinstance(eval_data, DataBatch):
            self.forward(eval_data, is_train=False)
            return self.get_outputs()
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outs = [o[0:o.shape[0] - pad] for o in self.get_outputs()]
            output_list.append(outs)
        if not merge_batches:
            return output_list
        num_outputs = len(output_list[0])
        merged = [nd.concatenate([o[i] for o in output_list], axis=0)
                  for i in range(num_outputs)]
        if num_outputs == 1 and not always_output_list:
            return merged[0]
        return merged

    # ------------------------------------------------------------ fit
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None,
            aux_params=None, allow_missing=False, force_rebind=False,
            force_init=False, begin_epoch=0, num_epoch=None,
            validation_metric=None, monitor=None,
            checkpoint_prefix=None):
        """Train on a data iterator (ref: base_module.py fit:376).

        ``checkpoint_prefix`` arms the divergence rollback of the
        step sentinel (docs/numeric_stability.md): when the guarded
        update path raises ``DivergedError`` (MXTPU_MAX_BAD_STEPS
        consecutive non-finite steps), fit restores the newest valid
        ``prefix-NNNN.params`` checkpoint — params, optimizer
        ``.states``, and the ``.data`` input-pipeline companion, so a
        relaunch resumes at the right batch — before re-raising for
        the launcher restart loop."""
        assert num_epoch is not None, "num_epoch must be given"
        telemetry.maybe_start_emitter()
        initializer = initializer or init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params,
                         allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        try:
            self._fit_epochs(train_data, eval_data, eval_metric,
                             epoch_end_callback, batch_end_callback,
                             eval_end_callback,
                             eval_batch_end_callback, begin_epoch,
                             num_epoch, validation_metric, monitor)
        except DivergedError:
            if checkpoint_prefix is not None:
                self.rollback_checkpoint(checkpoint_prefix,
                                         data_iter=train_data)
            raise

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    epoch_end_callback, batch_end_callback,
                    eval_end_callback, eval_batch_end_callback,
                    begin_epoch, num_epoch, validation_metric,
                    monitor):
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            data_iter = iter(train_data)
            # per-step timeline (docs/observability.md): data-wait /
            # forward-backward / optimizer / host-sync spans.  Spans
            # time wall-clock sections only — no device reads beyond
            # what the section already performs (update_metric's
            # host pull, the sentinel's guard-interval read), so the
            # transfer budget is unchanged.  The captured elapsed
            # times additionally feed the online anomaly watchdog
            # and the debugz statusz publish (host-side floats).
            watch = telemetry.anomaly_watch("train")
            while True:
                sp_data = telemetry.span("data_wait")
                with sp_data:
                    data_batch = next(data_iter, None)
                if data_batch is None:
                    break
                if monitor is not None:
                    monitor.tic()
                sp_fb = telemetry.span("forward_backward")
                with sp_fb:
                    self.forward_backward(data_batch)
                sp_opt = telemetry.span("optimizer")
                with sp_opt:
                    self.update()
                sp_sync = telemetry.span("host_sync")
                with sp_sync:
                    self.update_metric(eval_metric, data_batch.label)
                split = {"data_wait": sp_data.elapsed,
                         "forward_backward": sp_fb.elapsed,
                         "optimizer": sp_opt.elapsed,
                         "host_sync": sp_sync.elapsed}
                watch.observe(split)
                debugz.publish("train", step=nbatch, epoch=epoch,
                               split=split)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch, nbatch, eval_metric,
                                          locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                 val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=
                                 eval_batch_end_callback, epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)

    # ------------------------------------------------------------ rollback
    def rollback_checkpoint(self, prefix, data_iter=None):
        """Restore the newest valid ``prefix-NNNN.params`` checkpoint
        into this (bound) module after divergence: parameters, the
        optimizer ``.states`` companion (degrading to fresh state
        with a warning when missing/corrupt — same contract as
        resume), and the ``.data`` input-pipeline companion when
        ``data_iter`` supports it, so the stream resumes at the batch
        the checkpoint was taken at.  Returns the epoch restored, or
        None when no checkpoint validates (params left as they are —
        the caller's re-raise still hands the decision to the
        launcher)."""
        import warnings

        from ..model import (_checkpoint_epochs, load_checkpoint,
                             checkpoint_companion_path,
                             load_data_state)
        from ..resilience import CheckpointCorruptError
        epochs = _checkpoint_epochs(prefix)
        if not epochs:
            warnings.warn(
                f"divergence rollback: no checkpoints found under "
                f"prefix {prefix!r}; parameters left as-is",
                RuntimeWarning)
            return None
        newest = epochs[0][0]
        try:
            _, arg_params, aux_params, eff = load_checkpoint(
                prefix, newest, return_epoch=True)
        except CheckpointCorruptError as exc:
            warnings.warn(
                f"divergence rollback: no checkpoint under prefix "
                f"{prefix!r} validates ({exc}); parameters left "
                "as-is", RuntimeWarning)
            return None
        self.set_params(arg_params, aux_params, force_init=True)
        if self.optimizer_initialized and \
                hasattr(self, "load_optimizer_states"):
            states = checkpoint_companion_path(prefix, eff)
            try:
                self.load_optimizer_states(states)
            except (FileNotFoundError, CheckpointCorruptError) as exc:
                warnings.warn(
                    f"divergence rollback: optimizer states {states} "
                    f"could not be loaded ({exc}); continuing with "
                    "the diverged optimizer state replaced by the "
                    "restored weights only", RuntimeWarning)
        if data_iter is not None and \
                hasattr(data_iter, "load_state_dict"):
            load_data_state(prefix, eff, data_iter, strict=False)
        telemetry.counter("rollbacks_total").inc()
        warnings.warn(
            f"training diverged; rolled back to checkpoint epoch "
            f"{eff} of prefix {prefix!r} (params + optimizer + "
            "data-iterator state)", RuntimeWarning)
        return eff

    def install_monitor(self, mon):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
