"""Device-mesh construction and sharding helpers.

TPU-native replacement for the reference's device-placement machinery
(ref: src/executor/graph_executor.cc PlaceDevice/group2ctx :337-411 and
the multi-device Comm trees in src/kvstore/comm.h): instead of manual
per-layer device assignment plus explicit cross-device copies, the new
framework lays parameters and activations out over a named
`jax.sharding.Mesh` and lets XLA insert the collectives (psum /
all-gather / reduce-scatter / collective-permute) over ICI.

Axis conventions (the framework's canonical mesh axes):
  dp — data parallel (batch dimension)
  pp — pipeline parallel (layer stages)
  sp — sequence/context parallel (ring attention shards this axis)
  tp — tensor parallel (innermost: highest-bandwidth ICI neighbours)
  ep — expert parallel (MoE routing)

Axis order in the mesh is outermost→innermost [dp, pp, sp, tp, ep] so
that tensor-parallel collectives ride the shortest ICI hops — the
analog of the reference's preference for P2P rings between nearby GPUs
(ref: src/kvstore/comm.h CommDevice:471, MXNET_ENABLE_GPU_P2P).
"""
import contextlib

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["AXES", "make_mesh", "current_mesh", "use_mesh",
           "named_sharding", "replicated", "shard_batch", "P"]

P = PartitionSpec

AXES = ("dp", "pp", "sp", "tp", "ep")

_mesh_stack = []


def make_mesh(dp=None, pp=1, sp=1, tp=1, ep=1, devices=None):
    """Build a named Mesh over the available devices.

    ``dp=None`` means "whatever is left": dp = n_devices/(pp*sp*tp*ep).
    All five canonical axes are always present (size-1 axes are free),
    so PartitionSpecs can mention any of them unconditionally.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    model = pp * sp * tp * ep
    if dp is None:
        if n % model != 0:
            raise ValueError(
                f"{n} devices not divisible by pp*sp*tp*ep={model}")
        dp = n // model
    want = dp * model
    if want > n:
        raise ValueError(
            f"mesh {dp}x{pp}x{sp}x{tp}x{ep}={want} exceeds "
            f"{n} devices")
    dev_array = np.array(devices[:want]).reshape(dp, pp, sp, tp, ep)
    return Mesh(dev_array, AXES)


def current_mesh():
    """Innermost active mesh installed by :func:`use_mesh` (or None)."""
    return _mesh_stack[-1] if _mesh_stack else None


@contextlib.contextmanager
def use_mesh(mesh):
    """Install ``mesh`` as the ambient mesh for trainers/kvstore."""
    _mesh_stack.append(mesh)
    try:
        yield mesh
    finally:
        _mesh_stack.pop()


def named_sharding(mesh, *spec):
    """NamedSharding for ``spec`` (axis names / None per dimension)."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def shard_batch(mesh, ndim, batch_axis=0, seq_axis=None):
    """Sharding for an activation/batch tensor: batch dim over ('dp',),
    optionally a sequence dim over ('sp',)."""
    spec = [None] * ndim
    spec[batch_axis] = "dp"
    if seq_axis is not None:
        spec[seq_axis] = "sp"
    return NamedSharding(mesh, PartitionSpec(*spec))
