"""Parameter sharding rules: regex -> PartitionSpec.

The reference expresses model parallelism as per-node device groups
(`__ctx_group__` + PlaceDevice inserting _CrossDeviceCopy, ref:
src/executor/graph_executor.cc:337-411).  The TPU-native form is
declarative: a table of (parameter-name regex -> PartitionSpec) that
annotates how each weight is laid out over the mesh; XLA then derives
the collectives.  Defaults implement Megatron-style tensor parallelism
for Dense/Conv pairs:

- column-parallel matmul: shard the output-features dim over 'tp'
  (activations become tp-sharded, no collective needed going in);
- row-parallel matmul: shard the input-features dim over 'tp'
  (XLA inserts the psum on the way out);
- embeddings: shard the vocab dim over 'tp';
- everything else (biases, norm scales): replicated.
"""
import re

from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["ShardingRules", "tp_rules_for_dense_stacks",
            "apply_rules", "constrain", "spec_to_json",
            "spec_from_json", "bounds_of", "shard_bounds",
            "intersect_bounds"]

P = PartitionSpec


class ShardingRules:
    """Ordered (regex, PartitionSpec) table with a replicated default."""

    def __init__(self, rules=None, default=P()):
        self.rules = [(re.compile(pat), spec)
                      for pat, spec in (rules or [])]
        self.default = default

    def spec_for(self, name, ndim=None):
        """Spec for `name`; if ndim is given, specs longer than the
        array rank fall back to replicated rather than failing deep
        inside jax."""
        for pat, spec in self.rules:
            if pat.search(name):
                if ndim is not None and len(spec) > ndim:
                    return self.default
                return spec
        return self.default

    def shardings(self, mesh, params):
        """Dict of NamedShardings matching a params dict pytree."""
        return {n: NamedSharding(mesh, self.spec_for(n, v.ndim))
                for n, v in params.items()}

    def restrict_to_axes(self, axis_names):
        """Copy with rules referencing absent mesh axes dropped (their
        params fall back to replicated).  Lets one default rule table
        serve meshes that define only a subset of the standard axes
        (e.g. a hand-built Mesh with ('dp', 'ep') but no 'tp')."""
        axes = set(axis_names)

        def ok(spec):
            for el in spec:
                if el is None:
                    continue
                els = el if isinstance(el, tuple) else (el,)
                if any(a not in axes for a in els):
                    return False
            return True

        return ShardingRules(
            [(pat.pattern, spec) for pat, spec in self.rules
             if ok(spec)], self.default)


def tp_rules_for_dense_stacks():
    """Default Megatron-ish rules for blocks built from Dense layers
    named `*_up_*`/`*_down_*` (or `*col*`/`*row*`): up/col projections
    are column-parallel, down/row projections row-parallel.

    Dense weight layout in this framework is (out_features,
    in_features) — the reference FullyConnected convention
    (ref: src/operator/fully_connected-inl.h weight shape).
    """
    return ShardingRules([
        # expert-parallel (MoE): stacked expert weights shard their
        # leading expert dim over 'ep' — GSPMD derives the token
        # all-to-alls around the expert einsums (ops/moe.py)
        (r"expert_(up|down)_weight$", P("ep", None, None)),
        (r"expert_(up|down)_bias$", P("ep", None)),
        (r"(_up_|col|qkv|gate)\w*weight$", P("tp", None)),
        (r"(_down_|row|proj_o|out_proj)\w*weight$", P(None, "tp")),
        (r"(_up_|col|qkv|gate)\w*bias$", P("tp")),
        (r"embedding\w*weight$", P("tp", None)),
    ])


def apply_rules(mesh, params, rules):
    """Device-put each param with its rule's NamedSharding."""
    import jax
    shardings = rules.shardings(mesh, params)
    return {n: jax.device_put(v, shardings[n])
            for n, v in params.items()}


def constrain(x, mesh, *spec):
    """In-trace sharding constraint (activation annotation)."""
    import jax
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


# ---------------------------------------------------------------------------
# spec-driven slice arithmetic (the reshardable-checkpoint substrate,
# parallel/checkpoint.py / docs/elastic.md): a PartitionSpec over a
# mesh partitions an array into rectangular slices; saving records the
# slices each rank owns, loading intersects a *destination* slice with
# the recorded source slices so a restore reads only the shard files
# that overlap it — on any mesh shape, world size, or spec.
# ---------------------------------------------------------------------------


def spec_to_json(spec):
    """PartitionSpec -> JSON-able list (None | axis | [axes...] per
    dim), the manifest's layout record."""
    out = []
    for el in tuple(spec):
        if el is None or isinstance(el, str):
            out.append(el)
        else:
            out.append(list(el))
    return out


def spec_from_json(data):
    """Inverse of :func:`spec_to_json`."""
    return PartitionSpec(*[
        tuple(el) if isinstance(el, list) else el for el in data])


def bounds_of(idx, shape):
    """Normalize a devices_indices_map index (tuple of slices with
    None defaults) to a bounds tuple ``((lo, hi), ...)``, one
    closed-open interval per dim — the ONE definition of the
    index→bounds rule, shared by the save and load sides of the
    sharded checkpoint (a skew between them would corrupt
    restores)."""
    return tuple((0 if s.start is None else int(s.start),
                  int(dim) if s.stop is None else int(s.stop))
                 for s, dim in zip(idx, shape))


def shard_bounds(sharding, shape):
    """Partition an array of ``shape`` by ``sharding`` into unique
    rectangular slices: dict mapping a bounds tuple
    ``((lo, hi), ...)`` (one closed-open interval per dim) to the
    mesh devices holding that slice, sorted by device id — the first
    device is the slice's canonical *owner* (the one rank that writes
    it, so save cost is O(params/world) under replication)."""
    shape = tuple(int(d) for d in shape)
    out = {}
    for dev, idx in sharding.devices_indices_map(shape).items():
        out.setdefault(bounds_of(idx, shape), []).append(dev)
    return {b: sorted(devs, key=lambda d: d.id)
            for b, devs in out.items()}


def intersect_bounds(a, b):
    """Intersection of two bounds tuples, or None when disjoint
    (0-d bounds ``()`` intersect to ``()``)."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)
