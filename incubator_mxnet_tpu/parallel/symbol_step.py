"""SymbolTrainStep: one compiled fwd+bwd+optimizer step for a Symbol
graph over a device mesh — the `kvstore='tpu'` execution path of the
Module frontend.

This replaces the reference's DataParallelExecutorGroup, which slices
each batch across devices and allreduces gradients through KVStore
(ref: python/mxnet/module/executor_group.py:99,
python/mxnet/model.py _update_params_on_kvstore:105).  Here the whole
training iteration — graph forward, implicit-loss backward (the
Output-op ones-cotangent contract), gradient mean over the 'dp' mesh
axis (XLA inserts the psum), and the functional optimizer update — is
a single jit executable whose batch inputs are laid out sharded over
'dp'.

Learning rate is a *traced scalar argument* so lr schedulers step
without recompiling; lr_mult/wd_mult become per-leaf multiplier trees
(ref: python/mxnet/optimizer.py _get_lr/_get_wd).
"""
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import tracing
from ..executor import build_graph_fn, _ones_ct
from .data_parallel import _owned_put_tree, _copy_tree
from .mesh import make_mesh, replicated, shard_batch
from . import optim as foptim

__all__ = ["SymbolTrainStep"]


class SymbolTrainStep:
    """Compiled mesh training step over a bound Symbol.

    Parameters
    ----------
    symbol : Symbol — the full graph incl. loss-output heads
    param_vals / aux_vals : dict[str, jax.Array] initial values
    input_names : ordered data+label variable names fed per batch
    optimizer : FunctionalOptimizer (or name) applied in-jit
    rescale_grad : float — reference Module semantics (1/global-batch)
    lr_mults / wd_mults : per-param multipliers (name -> float)
    """

    def __init__(self, symbol, param_vals, aux_vals, input_names,
                 optimizer="sgd", optimizer_params=None, mesh=None,
                 rescale_grad=1.0, lr_mults=None, wd_mults=None,
                 batch_axis=0, numeric_guard=False,
                 guard_select=None):
        self.mesh = mesh if mesh is not None else make_mesh()
        # numeric_guard=True compiles the step-sentinel variant: the
        # gradients reduce to one in-jit finiteness scalar
        # (optimizer.all_finite), exposed as ``last_finite`` for the
        # host's guard-interval read.  With ``guard_select`` (default
        # = guarded; pass False for policy=warn, whose contract is to
        # apply bad updates) the whole update — params, aux,
        # optimizer state — additionally goes through a
        # where(finite, new, old) select, so EVERY step is protected
        # on device.  A traced ``poison`` multiplier carries the
        # grad:nonfinite fault injection without recompiles
        # (docs/numeric_stability.md).
        self._guarded = bool(numeric_guard)
        self._guard_select = self._guarded if guard_select is None \
            else bool(guard_select)
        self.last_finite = None
        # the mesh step compiles the same optimized graph the
        # single-device Executor does (MXTPU_GRAPH_OPT; rng fold
        # indices are pinned, so the dropout stream is unchanged)
        from ..graph.passes import optimize_symbol
        run_symbol, self.graph_report = optimize_symbol(symbol)
        self._symbol = run_symbol
        self._run = build_graph_fn(run_symbol)
        # perf observatory: analytic cost + MFU clock, armed on the
        # first compile when concrete batch shapes are known
        self.cost_report = None
        self._perf_clock = None
        self._param_names = tuple(sorted(param_vals))
        self._input_names = tuple(input_names)
        self._batch_axis = batch_axis
        if isinstance(optimizer, str):
            self.opt = foptim.create(optimizer,
                                     **(optimizer_params or {}))
        else:
            self.opt = optimizer
        self.rescale_grad = float(rescale_grad)
        self._lr_mults = {n: float((lr_mults or {}).get(n, 1.0))
                          for n in self._param_names}
        self._wd_mults = {n: float((wd_mults or {}).get(n, 1.0))
                          for n in self._param_names}

        rep = {n: replicated(self.mesh) for n in param_vals}
        self.params = _owned_put_tree(dict(param_vals), rep)
        arep = {n: replicated(self.mesh) for n in aux_vals}
        self.aux = _owned_put_tree(dict(aux_vals), arep)
        self.opt_state = self.opt.init(self.params)
        self._step = None
        self._eval = None
        # preflight HBM gate (docs/memory.md): plan accepted at the
        # first call, before the compile; None when planning failed
        self._mem_plan = None
        # device-memory attribution (docs/observability.md): the
        # step owns the job's params and optimizer state on device;
        # weakref providers so a dropped step stops being counted
        def _param_arrays(st):
            return list(st.params.values()) + list(st.aux.values())

        def _opt_arrays(st):
            return jax.tree_util.tree_leaves(st.opt_state)

        self._mem_unregister = tracing.register_param_opt_providers(
            self, _param_arrays, _opt_arrays)

    # ------------------------------------------------------------ build
    def _in_shard(self, ndim):
        return shard_batch(self.mesh, ndim, self._batch_axis)

    def _build(self, inputs):
        run, opt = self._run, self.opt
        pnames = self._param_names
        scale = self.rescale_grad
        lr_mults, wd_mults = self._lr_mults, self._wd_mults
        guarded = self._guarded
        guard_select = self._guard_select

        def step(params, aux, opt_state, inputs, rng, lr, poison):
            def inner(pvals):
                merged = dict(inputs)
                merged.update(zip(pnames, pvals))
                outs, aux_upd = run(merged, aux, rng, True)
                return outs, aux_upd

            primals = tuple(params[n] for n in pnames)
            (outs, aux_upd), vjp = jax.vjp(inner, primals)
            cts = [_ones_ct(o) for o in outs]
            aux_ct = {k: (np.zeros(v.shape, jax.dtypes.float0)
                          if not jnp.issubdtype(v.dtype, jnp.floating)
                          else jnp.zeros(v.shape, v.dtype))
                      for k, v in aux_upd.items()}
            (gvals,) = vjp((cts, aux_ct))
            grads = dict(zip(pnames, gvals))
            if guarded:
                grads = {n: g * poison.astype(g.dtype)
                         for n, g in grads.items()}
            new_params, new_opt = opt.update(
                params, grads, opt_state, scale=scale, lr=lr,
                lr_mults=lr_mults, wd_mults=wd_mults)
            new_aux = dict(aux)
            new_aux.update(aux_upd)
            if not guarded:
                return new_params, new_aux, new_opt, outs, True
            from ..optimizer import all_finite
            finite = jnp.asarray(all_finite(list(grads.values())))
            if not guard_select:
                return new_params, new_aux, new_opt, outs, finite
            sel = lambda new, old: jax.tree_util.tree_map(
                lambda a, b: jnp.where(finite, a, b), new, old)
            # a bad step must leave params, batchnorm-style aux
            # updates, AND optimizer state untouched — on device,
            # every step, regardless of host read cadence
            return (sel(new_params, params), sel(new_aux, dict(aux)),
                    sel(new_opt, opt_state), outs, finite)

        rep = replicated(self.mesh)
        p_sh = {n: rep for n in self.params}
        a_sh = {n: rep for n in self.aux}
        in_sh = {n: self._in_shard(v.ndim) for n, v in inputs.items()}
        return jax.jit(
            step,
            in_shardings=(p_sh, a_sh, None, in_sh, None, None, None),
            out_shardings=(p_sh, a_sh, None, None, None),
            donate_argnums=(0, 1, 2))

    def _preflight(self, vals):
        """Consult the analytic HBM plan (docs/memory.md) before the
        first compile.  This step fixes remat/grad_accum at graph
        construction, so the ladder has no rungs here: the plan
        either fits (within MXTPU_MEM_GATE_MARGIN), warns, or raises
        a typed MemoryPlanError per MXTPU_MEM_POLICY.  Planner
        failures on exotic graphs are non-fatal."""
        from ..perf import memory_planner as mp
        from ..resilience import MemoryPlanError
        try:
            shapes = {n: tuple(v.shape) for n, v in vals.items()}
            shapes.update({n: tuple(v.shape)
                           for n, v in self.params.items()})
            shapes.update({n: tuple(v.shape)
                           for n, v in dict(self.aux).items()})
            dtypes = {n: str(v.dtype) for n, v in vals.items()}
            live = mp.symbol_liveness(
                self._symbol, shapes, dtypes=dtypes,
                input_names=[n for n in self._input_names
                             if n in shapes])
            res = mp.preflight(
                lambda r, a: mp.plan_memory(
                    liveness=live,
                    params_bytes=mp.tree_bytes(self.params)
                    + mp.tree_bytes(dict(self.aux)),
                    max_param_bytes=mp.max_leaf_bytes(self.params),
                    optimizer_bytes=mp.tree_bytes(self.opt_state),
                    grad_accum=a, remat=r, donate=True,
                    batch_shards=int(self.mesh.shape.get("dp", 1))),
                site="symbol_train_step",
                device=self.mesh.devices.flat[0])
        except MemoryPlanError:
            raise
        except Exception:
            import logging
            logging.getLogger("mxtpu.memory").debug(
                "memory preflight skipped (planning failed)",
                exc_info=True)
            return
        if res is not None:
            self._mem_plan = res.plan

    # ------------------------------------------------------------ run
    def __call__(self, inputs, rng=None, lr=0.01):
        """Run one step on a global batch.

        inputs: dict name -> array (host or device); returns the list
        of output arrays (replicated loss heads / sharded outputs).
        """
        from ..dist import elastic_probe
        elastic_probe()     # elastic:rank<N> injection (docs/elastic.md)
        if rng is None:
            from .. import random_state
            rng = random_state.next_key()
        from ..resilience import as_oom_error, check_oom
        vals = {n: jnp.asarray(v) if not isinstance(v, jax.Array)
                else v for n, v in inputs.items()}
        compiled = self._step is None
        t0 = time.monotonic()
        try:
            if compiled:
                self._preflight(vals)
                self._step = self._build(vals)
            # mem:oom injection point; free without MXTPU_FAULT_SPEC
            check_oom("symbol_train_step")
            vals = {n: jax.device_put(v, self._in_shard(v.ndim))
                    for n, v in vals.items()}
            poison = 1.0
            if self._guarded:
                from ..optimizer import grad_poison
                poison = grad_poison() or 1.0
            (self.params, self.aux, self.opt_state, outs,
             self.last_finite) = self._step(
                self.params, self.aux, self.opt_state, vals, rng,
                jnp.asarray(lr, jnp.float32),
                jnp.asarray(poison, jnp.float32))
        except Exception as exc:
            # route real RESOURCE_EXHAUSTED (and the injected kind)
            # through the typed guard; this step has no runtime
            # degrade rungs, so the OomError stays loud
            oom = as_oom_error(exc, "symbol_train_step",
                               plan=self._mem_plan)
            if oom is None:
                raise
            raise oom from exc
        if compiled:
            cost = self._arm_perf(vals)
            # first call = trace + compile of the whole mesh step;
            # recorded with the batch signature so a rebuilt step
            # (fresh Module bind / rollback) attributes what differed
            tracing.compile_ledger("symbol_train_step").record(
                {"shape": tuple(sorted(
                    (n, tuple(v.shape)) for n, v in vals.items())),
                 "dtype": tuple(sorted(
                     (n, str(v.dtype)) for n, v in vals.items())),
                 "train_flag": True},
                time.monotonic() - t0, cost=cost)
        if self._perf_clock is not None:
            self._perf_clock.tick()
        return outs

    def _arm_perf(self, vals):
        """Cost the optimized graph at the first batch's shapes (a
        shape-only eval_shape walk — bind-time, never the step path)
        and arm the train_mfu/train_mbu clock.  Returns the compile
        ledger's cost summary, or None when costing fails."""
        try:
            from ..perf import TrainPerfClock, symbol_cost
            shapes = {n: tuple(v.shape) for n, v in vals.items()}
            shapes.update({n: tuple(v.shape)
                           for n, v in self.params.items()})
            shapes.update({n: tuple(v.shape)
                           for n, v in dict(self.aux).items()})
            # train step ~= 3x the forward graph (fwd + bwd)
            self.cost_report = symbol_cost(self._symbol,
                                           shapes).scaled(3.0)
            dtype = str(next(iter(self.params.values())).dtype) \
                if self.params else "float32"
            self._perf_clock = TrainPerfClock(
                self.cost_report.flops, self.cost_report.bytes,
                dtype=dtype)
            return self.cost_report.summary()
        except Exception:
            self.cost_report = None
            self._perf_clock = None
            return None

    def evaluate(self, inputs, rng=None):
        """Compiled inference forward over the mesh (score/predict)."""
        if rng is None:
            from .. import random_state
            rng = random_state.next_key()
        run = self._run
        if self._eval is None:
            def ev(params, aux, inputs, rng):
                merged = dict(inputs)
                merged.update(params)
                outs, _ = run(merged, aux, rng, False)
                return outs
            self._eval = jax.jit(ev)
        vals = {n: jax.device_put(jnp.asarray(v),
                                  self._in_shard(jnp.asarray(v).ndim))
                for n, v in inputs.items()}
        return self._eval(self.params, self.aux, vals, rng)

    # ------------------------------------------------------------ values
    @property
    def input_names(self):
        """Per-batch graph inputs (data + label variable names)."""
        return self._input_names

    def owned_values(self):
        """(params, aux) copies safe to hand to external holders —
        the step's own buffers are donated next call."""
        return _copy_tree(self.params), _copy_tree(dict(self.aux))

    def set_values(self, param_vals, aux_vals):
        """Replace the step's device values (e.g. after an external
        eager update touched the frontend's copies)."""
        rep = {n: replicated(self.mesh) for n in param_vals}
        self.params = _owned_put_tree(dict(param_vals), rep)
        arep = {n: replicated(self.mesh) for n in aux_vals}
        self.aux = _owned_put_tree(dict(aux_vals), arep)

    # ---------------------------------------------------------- checkpoint
    def save_checkpoint(self, path, step=None, data_state=None):
        """Write params + aux + optimizer state as one sharded
        generation under ``path`` (parallel/checkpoint.py manifest
        format, docs/elastic.md) — the Module frontend's elastic
        checkpoint: each rank writes only its owned slices, and the
        input iterator's ``data_state`` rides in the same generation.
        Returns the generation directory."""
        from . import checkpoint as _ckpt
        tree = {"params": _copy_tree(self.params),
                "aux": _copy_tree(dict(self.aux)),
                "opt_state": _copy_tree(self.opt_state)}
        return _ckpt.save_sharded(
            path, tree, self.mesh, step=step, data_state=data_state,
            extra={"optimizer": foptim.state_structure(
                self.opt_state)})

    def load_checkpoint(self, path):
        """Restore the newest valid generation INTO this step's mesh
        layout — reassembled per-shard from the overlapping source
        slices, so the saving job's mesh shape / world size need not
        match this one's.  Returns the generation's data-iterator
        companion state (or None)."""
        from . import checkpoint as _ckpt
        tree = {"params": self.params, "aux": dict(self.aux),
                "opt_state": self.opt_state}
        restored, manifest, gen_dir = _ckpt.load_latest(
            path, tree, self.mesh)
        self.params = restored["params"]
        self.aux = restored["aux"]
        self.opt_state = restored["opt_state"]
        return _ckpt.load_data_companion(gen_dir, manifest)
