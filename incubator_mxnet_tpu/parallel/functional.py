"""Extract a pure, jit-traceable apply function from a Gluon block.

This is the bridge between the imperative Gluon frontend and the
sharded/compiled training world: the same trick HybridBlock's cache
uses (gluon/block.py _build_cache; ref: src/imperative/cached_op.cc
GetForwardGraph:171) exposed as a standalone utility, returning

    apply(params: dict[str, jax.Array], inputs, rng, training)
        -> (outputs: list[jax.Array], new_states: dict[str, jax.Array])

plus the current parameter values split into trainable params and
non-trainable states (BatchNorm moving stats — the reference's
auxiliary states, ref: include/mxnet/operator.h aux_states).
"""

from .. import autograd, random_state
from ..ndarray.ndarray import NDArray

__all__ = ["functionalize", "PureBlock"]


class PureBlock:
    """A Gluon block lowered to a pure function + parameter pytrees."""

    def __init__(self, block):
        params = block.collect_params()
        self._names = sorted(params.keys())
        self._objs = [params[n] for n in self._names]
        self._block = block
        self.trainable_names = [n for n, p in zip(self._names, self._objs)
                                if p.grad_req != "null"]
        self.state_names = [n for n, p in zip(self._names, self._objs)
                            if p.grad_req == "null"]

    # ------------------------------------------------------------ values
    def params(self):
        """Current trainable parameter values as a flat dict pytree."""
        d = dict(zip(self._names, (p.data()._data for p in self._objs)))
        return {n: d[n] for n in self.trainable_names}

    def states(self):
        d = dict(zip(self._names, (p.data()._data for p in self._objs)))
        return {n: d[n] for n in self.state_names}

    def write_back(self, params=None, states=None):
        """Write updated values back into the live Parameter objects."""
        byname = dict(zip(self._names, self._objs))
        for src in (params, states):
            if src:
                for n, v in src.items():
                    byname[n]._data._data = v

    # ------------------------------------------------------------ apply
    def apply(self, params, states, inputs, rng, training=True):
        """Pure forward: substitute values, run the block's Python
        forward (tracers flow through the NDArray ops), restore."""
        merged = dict(params)
        merged.update(states)
        objs = self._objs
        saved = [(p, p._data._data) for p in objs]
        prev_rec = autograd.set_recording(False)
        prev_train = autograd.set_training(training)
        try:
            for n, p in zip(self._names, objs):
                p._data._data = merged[n]
            with random_state.key_provider(rng):
                outs = self._block.forward(
                    *[NDArray(v) for v in inputs])
            out_list = outs if isinstance(outs, (list, tuple)) else [outs]
            out_vals = [o._data for o in out_list]
            new_states = {n: p._data._data
                          for n, p in zip(self._names, objs)
                          if n in self.state_names}
        finally:
            for p, v in saved:
                p._data._data = v
            autograd.set_recording(prev_rec)
            autograd.set_training(prev_train)
        return out_vals, new_states


def functionalize(block, *example_args):
    """Settle deferred shapes with one eager forward, then return a
    :class:`PureBlock`.  ``example_args`` are NDArrays (or jax arrays)."""
    nds = [a if isinstance(a, NDArray) else NDArray(a)
           for a in example_args]
    if nds:
        with autograd.pause():
            block.forward(*nds)
    return PureBlock(block)
