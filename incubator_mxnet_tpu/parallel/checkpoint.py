"""Sharded, reshardable checkpoints with a manifest
(docs/elastic.md).

The reference's distributed story tolerates a dying worker (ps-lite
restarts it) but a restart assumes the *same world*: same mesh shape,
same world size, same data-worker count.  This module is the layer
that removes that assumption:

- **Sharded save** — each rank writes only the parameter/optimizer
  slices it canonically owns (one file per owner device, written via
  ``resilience.atomic_save`` + CRC32 sidecar), so save cost is
  O(params/world) instead of every rank serializing the full pytree.
- **Manifest** — rank 0 writes ``manifest.json`` LAST (the commit
  marker: a generation without a valid manifest does not exist):
  mesh axes/shape, per-leaf PartitionSpec + global shape/dtype, the
  slice->file map, the optimizer-state tree structure, step, and the
  data-iterator companion ref.
- **Topology-aware reshard on load** — a manifest restores onto a
  *different* mesh (dp×tp reshaped, world shrunk or grown): each
  destination shard is assembled by intersecting its bounds with the
  recorded source slices (parallel/sharding.py slice arithmetic), so
  a rank reads only the source shard files that overlap what it
  needs.
- **Generations + per-shard fallback** — saves land in
  ``gen-<step>/`` subdirectories; a corrupt shard or manifest fails
  that generation and the loader falls back to the newest fully
  valid one (PR 1 corrupt-load semantics, per shard), keeping
  ``MXTPU_CKPT_KEEP`` generations on disk.

Fault injection: ``checkpoint:shard:<nth>:truncate|corrupt|error``
damages (or fails) the nth shard-file write, deterministically
producing the torn states the fallback path defends against
(docs/resilience.md grammar).
"""
import json
import os
import pickle
import shutil

import numpy as np

import jax

from .. import telemetry, tracing
from .. import resilience
from ..utils.env import get_env
from .sharding import intersect_bounds, shard_bounds, spec_to_json

__all__ = ["save_sharded", "load_sharded", "load_latest",
           "generations", "load_data_companion", "FORMAT"]

FORMAT = "mxtpu-sharded-v1"

_MANIFEST = "manifest.json"
_DATA_COMPANION = "data.pkl"


# ---------------------------------------------------------------------------
# leaf flattening: stable string keys for arbitrary pytrees
# ---------------------------------------------------------------------------


def _flatten(tree):
    """(key, leaf) pairs with jax keystr paths — stable across
    processes and sessions (dict keys are sorted by tree_flatten)."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(jax.tree_util.keystr(path), leaf)
            for path, leaf in leaves]


def _named_sharding_for(leaf, mesh):
    """The leaf's NamedSharding when it is laid out over ``mesh``;
    None otherwise (single-device scalars, numpy arrays, fresh-init
    leaves) — those are treated as replicated."""
    sh = getattr(leaf, "sharding", None)
    if sh is None or not hasattr(sh, "devices_indices_map"):
        return None
    if not hasattr(sh, "spec"):        # SingleDeviceSharding etc.
        return None
    if getattr(sh, "num_devices", 0) != mesh.devices.size:
        return None
    return sh


def _leaf_np(leaf):
    return np.asarray(leaf)


def _full_bounds(shape):
    return tuple((0, int(d)) for d in shape)


def _rel_index(bounds, base):
    """Numpy index of ``bounds`` relative to a block starting at
    ``base`` lower corners."""
    return tuple(slice(lo - b0, hi - b0)
                 for (lo, hi), (b0, _) in zip(bounds, base))


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------


def _shard_file(owner_id):
    return f"shard-{owner_id:05d}.pkl"


def save_sharded(ckpt_dir, tree, mesh, step=None, data_state=None,
                 extra=None, keep=None):
    """Write one checkpoint generation of ``tree`` under
    ``ckpt_dir/gen-<step>/``; returns the generation directory.

    ``tree`` is any pytree of arrays (params / aux / optimizer state
    / counters).  Each leaf's layout is read off its own sharding:
    leaves on ``mesh`` save one file entry per *unique* slice,
    written by the slice's canonical owner; everything else saves as
    one replicated slice.  In a multi-process world every process
    calls this with the same tree and writes only the shard files
    whose owner devices it hosts; the process hosting device 0
    additionally writes the manifest (last — the commit marker).

    ``step`` defaults to one past the newest existing generation.
    ``data_state`` (a ``state_dict()`` from the input pipeline) is
    pickled next to the shards and recorded in the manifest so params
    and data cursors always travel together.  ``keep`` bounds the
    retained generations (default ``MXTPU_CKPT_KEEP``); pruning only
    ever runs on fully-committed older generations.
    """
    with telemetry.span("checkpoint_save"):
        return _save_sharded(ckpt_dir, tree, mesh, step, data_state,
                             extra, keep)


def _save_sharded(ckpt_dir, tree, mesh, step, data_state, extra,
                  keep):
    ckpt_dir = os.path.abspath(ckpt_dir)
    os.makedirs(ckpt_dir, exist_ok=True)
    if step is None:
        gens = generations(ckpt_dir, require_valid=False)
        step = (gens[0] + 1) if gens else 0
    gen_dir = os.path.join(ckpt_dir, f"gen-{int(step):08d}")
    os.makedirs(gen_dir, exist_ok=True)
    my_proc = jax.process_index()
    min_dev = min(d.id for d in mesh.devices.flat)

    # re-saving an existing step (fallback -> retrain -> same step):
    # UNCOMMIT the old generation first — unlink its manifest before
    # any shard is replaced, so no crash point can pair the old
    # manifest with a mix of old and new shard files (each one
    # individually CRC-valid = a silently frankensteined restore).
    # A crash mid-rewrite now leaves the generation invisible and
    # the loader falls back, per the commit contract.
    if my_proc == 0:
        for stale in (_MANIFEST,
                      resilience.checksum_path(_MANIFEST)):
            try:
                os.unlink(os.path.join(gen_dir, stale))
            except FileNotFoundError:
                pass
    # peers must not replace shards before the uncommit lands
    _sync_processes("mxtpu_ckpt_uncommit")

    files = {}          # owner id -> {slice key: np array}
    leaves = {}
    for key, leaf in _flatten(tree):
        sh = _named_sharding_for(leaf, mesh)
        shape = tuple(int(d) for d in leaf.shape)
        spec = spec_to_json(sh.spec) if sh is not None \
            else [None] * len(shape)
        slices = []
        if sh is None:
            bounds = _full_bounds(shape)
            name = f"{key}#0"
            slices.append({"lo": [b[0] for b in bounds],
                           "hi": [b[1] for b in bounds],
                           "file": _shard_file(min_dev),
                           "name": name})
            if _min_dev_proc(mesh) == my_proc:
                files.setdefault(min_dev, {})[name] = _leaf_np(leaf)
        else:
            by_dev = {s.device.id: s for s in leaf.addressable_shards}
            for i, (bounds, devs) in enumerate(
                    sorted(shard_bounds(sh, shape).items())):
                owner = devs[0]
                name = f"{key}#{i}"
                slices.append({"lo": [b[0] for b in bounds],
                               "hi": [b[1] for b in bounds],
                               "file": _shard_file(owner.id),
                               "name": name})
                if owner.process_index != my_proc:
                    continue
                files.setdefault(owner.id, {})[name] = \
                    np.asarray(by_dev[owner.id].data)
        leaves[key] = {"shape": list(shape),
                       "dtype": str(np.dtype(leaf.dtype)),
                       "spec": spec, "slices": slices}

    for owner_id, payload in sorted(files.items()):
        kind = resilience.inject("checkpoint", "shard")
        path = os.path.join(gen_dir, _shard_file(owner_id))
        resilience.atomic_save(
            path, lambda f, p=payload: pickle.dump(p, f, protocol=4))
        if kind in ("truncate", "corrupt"):
            # injected damage lands on the COMMITTED file, after its
            # sidecar was written from the healthy bytes — the
            # bit-rot state the CRC check must catch
            resilience.damage_file(path, kind)
        telemetry.counter("checkpoint_shard_saved_total").inc()

    data_ref = None
    if data_state is not None:
        # one companion per generation, written by the coordinating
        # process (per-rank input states across a multi-host world
        # are the multi-host tier's concern — ROADMAP item 5); in
        # the common layouts the input position is rank-0-owned or
        # identical across ranks
        if my_proc == 0:
            resilience.atomic_save(
                os.path.join(gen_dir, _DATA_COMPANION),
                lambda f: pickle.dump(data_state, f, protocol=4))
        data_ref = _DATA_COMPANION

    # "manifest written LAST" must hold across the whole world, not
    # just this process: rank 0 may not commit until every peer's
    # shard files are durably in place, or a kill in the window
    # leaves a valid-looking manifest referencing missing shards
    _sync_processes("mxtpu_ckpt_shards")
    if my_proc == 0:
        manifest = {
            "format": FORMAT,
            "step": int(step),
            "mesh": {"axes": list(mesh.axis_names),
                     "shape": [int(mesh.shape[a])
                               for a in mesh.axis_names]},
            "world": {"processes": int(jax.process_count()),
                      "devices": int(mesh.devices.size),
                      "generation": int(os.environ.get(
                          "MXTPU_WORLD_GENERATION", "0") or 0)},
            "leaves": leaves,
            "data": data_ref,
            "extra": extra or {},
        }
        resilience.atomic_write_bytes(
            os.path.join(gen_dir, _MANIFEST),
            json.dumps(manifest, indent=1, sort_keys=True).encode())
        _prune(ckpt_dir, keep)
    return gen_dir


def _min_dev_proc(mesh):
    """process_index hosting the mesh's lowest-id device (the
    canonical writer of replicated / off-mesh leaves)."""
    return min(mesh.devices.flat,
               key=lambda d: d.id).process_index


def _sync_processes(tag):
    """Cross-process ordering point for multi-process saves (no-op
    single-process, which is every CPU/virtual-mesh run).  Runs
    under the dist collective deadline so a peer that died
    mid-checkpoint surfaces as the usual typed failure instead of a
    wedged save."""
    if jax.process_count() > 1:
        from .. import dist
        dist.barrier(tag)


def _prune(ckpt_dir, keep):
    keep = int(keep if keep is not None else get_env("MXTPU_CKPT_KEEP"))
    if keep <= 0:
        return
    valid = generations(ckpt_dir)
    for step in valid[keep:]:
        shutil.rmtree(os.path.join(ckpt_dir, f"gen-{step:08d}"),
                      ignore_errors=True)
    # uncommitted (manifest-less) generations — a save killed between
    # its shard writes and the manifest commit — are invisible to the
    # loader but still hold O(params/world) of shard bytes; sweep any
    # OLDER than the newest valid generation (never newer: that is
    # where an in-flight save may be writing right now)
    if valid:
        stale = set(generations(ckpt_dir, require_valid=False)) \
            - set(valid)
        for step in stale:
            if step < valid[0]:
                shutil.rmtree(
                    os.path.join(ckpt_dir, f"gen-{step:08d}"),
                    ignore_errors=True)


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------


def generations(ckpt_dir, require_valid=True):
    """Generation steps under ``ckpt_dir``, newest first.  With
    ``require_valid`` (default) only generations whose manifest
    exists and passes its CRC sidecar count — a save that died before
    the manifest rename is invisible, exactly the commit contract."""
    out = []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return out
    for name in names:
        if not name.startswith("gen-"):
            continue
        stem = name[len("gen-"):]
        if not stem.isdigit():
            continue
        if require_valid:
            man = os.path.join(ckpt_dir, name, _MANIFEST)
            if not resilience.verify_checkpoint(man):
                continue
        out.append(int(stem))
    return sorted(out, reverse=True)


def _read_manifest(gen_dir):
    raw = resilience.read_validated_bytes(
        os.path.join(gen_dir, _MANIFEST))
    manifest = resilience.decode_or_corrupt(
        os.path.join(gen_dir, _MANIFEST), lambda: json.loads(raw))
    if manifest.get("format") != FORMAT:
        raise resilience.CheckpointCorruptError(
            f"{gen_dir}: unknown sharded-checkpoint format "
            f"{manifest.get('format')!r} (want {FORMAT})")
    return manifest


class _ShardReader:
    """Validated, cached access to a generation's shard files —
    each file is CRC-checked once and unpickled once, and only the
    files actually referenced by the requested slices are read."""

    def __init__(self, gen_dir):
        self.gen_dir = gen_dir
        self._cache = {}

    def slice_array(self, slc):
        fname = slc["file"]
        if fname not in self._cache:
            path = os.path.join(self.gen_dir, fname)
            raw = resilience.read_validated_bytes(path)
            self._cache[fname] = resilience.decode_or_corrupt(
                path, lambda: pickle.loads(raw))
        payload = self._cache[fname]
        if slc["name"] not in payload:
            raise resilience.CheckpointCorruptError(
                f"{self.gen_dir}/{fname}: missing slice "
                f"{slc['name']!r} (manifest/shard mismatch)")
        return payload[slc["name"]]


def _dest_sharding(leaf, mesh):
    """Destination layout for a target leaf: its own NamedSharding
    when it lives on ``mesh``, replicated-on-``mesh`` otherwise
    (fresh-init optimizer scalars live on one device; the restored
    tree must be mesh-consistent)."""
    from jax.sharding import NamedSharding, PartitionSpec
    sh = _named_sharding_for(leaf, mesh)
    return sh if sh is not None else NamedSharding(
        mesh, PartitionSpec())


def _assemble_block(entry, reader, bounds, shape, dtype):
    """Assemble ONE destination slice by intersecting its bounds
    with the manifest's source slices, copying only the overlapping
    regions."""
    block = np.empty([hi - lo for lo, hi in bounds], dtype)
    covered = 0
    for slc in entry["slices"]:
        src_b = tuple(zip(slc["lo"], slc["hi"]))
        inter = intersect_bounds(src_b, bounds)
        if inter is None:
            continue
        src = reader.slice_array(slc)
        if not bounds:          # 0-d leaf
            return np.asarray(src, dtype)
        block[_rel_index(inter, bounds)] = \
            src[_rel_index(inter, src_b)]
        covered += int(np.prod([hi - lo for lo, hi in inter]))
    want = int(np.prod([hi - lo for lo, hi in bounds])) \
        if bounds else 1
    if covered < want:
        raise resilience.CheckpointCorruptError(
            f"slice coverage hole restoring a leaf of shape "
            f"{shape}: {covered}/{want} elements — source and "
            "destination partitions disagree on the global shape")
    return block


def _assemble_leaf(entry, reader, dest_sh, shape, dtype):
    """Build one destination leaf.  Host assembly is done once per
    UNIQUE destination slice (replicated leaves and dp-replicated tp
    shards would otherwise redo identical multi-GB copies once per
    device); each device then gets a device_put of its shared
    block."""
    from .sharding import bounds_of
    by_bounds = {}
    blocks = {}
    for dev, idx in dest_sh.devices_indices_map(shape).items():
        if dev.process_index != jax.process_index():
            continue
        bounds = bounds_of(idx, shape)
        if bounds not in by_bounds:
            by_bounds[bounds] = _assemble_block(
                entry, reader, bounds, shape, dtype)
        blocks[dev] = jax.device_put(by_bounds[bounds], dev)
    return jax.make_array_from_single_device_arrays(
        shape, dest_sh, [blocks[d] for d in sorted(
            blocks, key=lambda d: d.id)])


def load_sharded(gen_dir, target_tree, mesh):
    """Restore one generation INTO the layout of ``target_tree``
    (a pytree of arrays — typically the live step state — whose
    shardings define the destination): returns (tree, manifest).

    The target's tree structure and per-leaf global shapes/dtypes
    must match the manifest — a mismatch is a loud error naming the
    offending keys, not a silent partial restore (restoring ZeRO or
    Adam state into a differently-structured optimizer would corrupt
    training invisibly)."""
    with telemetry.span("checkpoint_load"):
        manifest = _read_manifest(gen_dir)
        reader = _ShardReader(gen_dir)
        flat = _flatten(target_tree)
        want = {k for k, _ in flat}
        have = set(manifest["leaves"])
        if want != have:
            missing = sorted(want - have)
            extra = sorted(have - want)
            raise ValueError(
                f"sharded checkpoint {gen_dir} does not match the "
                f"target tree structure: missing={missing[:8]} "
                f"extra={extra[:8]} (optimizer/state trees must "
                "be built the same way they were saved)")
        out = {}
        for key, leaf in flat:
            entry = manifest["leaves"][key]
            shape = tuple(entry["shape"])
            dtype = np.dtype(leaf.dtype)
            if shape != tuple(int(d) for d in leaf.shape) \
                    or entry["dtype"] != str(dtype):
                raise ValueError(
                    f"sharded checkpoint {gen_dir} leaf {key}: "
                    f"saved {entry['shape']}/{entry['dtype']} vs "
                    f"target {tuple(leaf.shape)}/{dtype} — global "
                    "shapes/dtypes must match to reshard")
            dest_sh = _dest_sharding(leaf, mesh)
            out[key] = _assemble_leaf(entry, reader, dest_sh,
                                      shape, dtype)
        treedef = jax.tree_util.tree_structure(target_tree)
        keys = [k for k, _ in flat]
        tree = jax.tree_util.tree_unflatten(
            treedef, [out[k] for k in keys])
        return tree, manifest


def load_latest(ckpt_dir, target_tree, mesh):
    """Restore the newest fully-valid generation, falling back past
    corrupt shards/manifests generation by generation (warning +
    `checkpoint_shard_fallback` trace event each hop).  Returns
    (tree, manifest, gen_dir); raises CheckpointCorruptError when no
    generation restores."""
    import warnings
    gens = generations(ckpt_dir)
    if not gens:
        raise resilience.CheckpointCorruptError(
            f"no committed checkpoint generation under {ckpt_dir} "
            "(a save that died before its manifest rename leaves "
            "nothing visible, by design)")
    last_exc = None
    for i, step in enumerate(gens):
        gen_dir = os.path.join(ckpt_dir, f"gen-{step:08d}")
        try:
            tree, manifest = load_sharded(gen_dir, target_tree, mesh)
            return tree, manifest, gen_dir
        except (resilience.CheckpointCorruptError, OSError) as exc:
            last_exc = exc
            telemetry.counter("checkpoint_shard_corrupt_total").inc()
            if i + 1 < len(gens):
                tracing.trace_event(
                    "checkpoint_shard_fallback", from_gen=step,
                    to_gen=gens[i + 1], error=str(exc)[:200])
                warnings.warn(
                    f"sharded checkpoint generation {step} failed "
                    f"validation ({exc}); falling back to generation "
                    f"{gens[i + 1]}", RuntimeWarning)
    raise resilience.CheckpointCorruptError(
        f"every checkpoint generation under {ckpt_dir} failed "
        f"validation (newest error: {last_exc})")


def load_data_companion(gen_dir, manifest=None):
    """The data-iterator ``state_dict`` saved with a generation, or
    None when the save carried none (validated + typed like every
    checkpoint read)."""
    if manifest is None:
        manifest = _read_manifest(gen_dir)
    ref = manifest.get("data")
    if not ref:
        return None
    path = os.path.join(gen_dir, ref)
    raw = resilience.read_validated_bytes(path)
    return resilience.decode_or_corrupt(
        path, lambda: pickle.loads(raw))
