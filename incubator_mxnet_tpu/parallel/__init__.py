"""Distributed execution over TPU meshes.

The TPU-native replacement for the reference's distributed stack
(KVStore Comm trees, ps-lite parameter server, PlaceDevice model
parallelism — SURVEY.md §2.6), plus the new-capability parallelisms
the reference lacks (tensor, pipeline, sequence/ring).

  mesh            — named Mesh construction ('dp','pp','sp','tp','ep')
  functional      — Gluon block -> pure apply fn + param pytrees
  optim           — functional optimizers for compiled steps
  sharding        — parameter sharding rules (regex -> PartitionSpec)
  data_parallel   — ShardedTrainStep: one pjit step = fwd+bwd+psum+opt
  checkpoint      — sharded reshardable checkpoints with a manifest
                    (elastic shrink/grow restore, docs/elastic.md)
  pipeline        — GPipe-style scan pipeline over 'pp'
  ring_attention  — sequence parallelism over 'sp' (ppermute ring)
  ulysses_attention — sequence parallelism via all-to-all head
                    sharding (DeepSpeed-Ulysses scheme)
"""
from .mesh import (AXES, make_mesh, current_mesh, use_mesh,
                   named_sharding, replicated, shard_batch, P)
from .functional import functionalize, PureBlock
from . import optim
from .sharding import ShardingRules, tp_rules_for_dense_stacks, constrain
from .data_parallel import ShardedTrainStep
from .symbol_step import SymbolTrainStep
from .checkpoint import (save_sharded, load_sharded, load_latest,
                         load_data_companion)
from .pipeline import pipeline_apply, stack_stage_params
from .ring_attention import ring_attention, ring_attention_local
from .ulysses import ulysses_attention, ulysses_attention_local

__all__ = ["AXES", "make_mesh", "current_mesh", "use_mesh",
           "named_sharding", "replicated", "shard_batch", "P",
           "functionalize", "PureBlock", "optim", "ShardingRules",
           "tp_rules_for_dense_stacks", "constrain",
           "ShardedTrainStep", "SymbolTrainStep",
           "save_sharded", "load_sharded", "load_latest",
           "load_data_companion",
           "pipeline_apply", "stack_stage_params",
           "ring_attention", "ring_attention_local",
           "ulysses_attention", "ulysses_attention_local"]
