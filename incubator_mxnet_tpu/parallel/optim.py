"""Functional (pytree) optimizers for compiled sharded training steps.

The imperative optimizer zoo (optimizer.py) applies updates key-by-key
through the Updater, mirroring the reference's fused optimizer ops
(ref: src/operator/optimizer_op.cc sgd_update:39, sgd_mom_update:66,
adam_update:146, mp_sgd_update:111).  Inside a pjit-compiled train
step the idiomatic form is a pure ``(params, grads, state) ->
(params, state)`` transform over pytrees, so the whole update fuses
into the step executable and inherits the parameter sharding — the
XLA analog of `update_on_kvstore` running the optimizer where the
reduced gradient lives (ref: src/kvstore/kvstore_dist_server.h
ApplyUpdates:176).

Multi-precision (`mp_`) behavior: pass ``master_dtype=jnp.float32``
and keep bf16 compute params alongside fp32 master weights.
"""
import jax
import jax.numpy as jnp

__all__ = ["FunctionalOptimizer", "sgd", "adam", "create"]


def _tree_map(f, *trees, **kw):
    return jax.tree_util.tree_map(f, *trees, **kw)


class FunctionalOptimizer:
    """A pure optimizer: init(params)->state; update(...)->new pair."""

    def __init__(self, init_fn, update_fn, hyper):
        self._init = init_fn
        self._update = update_fn
        self.hyper = hyper

    def init(self, params):
        return self._init(params)

    def update(self, params, grads, state, scale=1.0):
        return self._update(params, grads, state, scale)


def sgd(learning_rate=0.01, momentum=0.0, wd=0.0, clip_gradient=None,
        nesterov=False):
    """SGD(+momentum, +wd) — semantics of the reference's sgd_update /
    sgd_mom_update kernels (ref: src/operator/optimizer_op.cc:39,66):
    grad = scale*grad [clipped] + wd*weight; mom = m*mom - lr*grad;
    weight += mom.  With ``nesterov=True``, NAG semantics (ref:
    python/mxnet/optimizer.py NAG:592): mom = m*mom + grad;
    weight -= lr*(grad + m*mom)."""
    lr, mom, wdec = learning_rate, momentum, wd

    def init_fn(params):
        if mom == 0.0:
            return {}
        return {"mom": _tree_map(jnp.zeros_like, params)}

    def update_fn(params, grads, state, scale):
        def one(w, g, m=None):
            g = g * scale
            if clip_gradient is not None:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            g = g + wdec * w
            if m is None:
                return w - lr * g, None
            if nesterov:
                m_new = mom * m + g
                return w - lr * (g + mom * m_new), m_new
            m_new = mom * m - lr * g
            return w + m_new, m_new

        if mom == 0.0:
            new_p = _tree_map(lambda w, g: one(w, g)[0], params, grads)
            return new_p, state
        pairs = _tree_map(lambda w, g, m: one(w, g, m),
                          params, grads, state["mom"])
        new_p = _tree_map(lambda pr: pr[0], pairs,
                          is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tree_map(lambda pr: pr[1], pairs,
                          is_leaf=lambda x: isinstance(x, tuple))
        return new_p, {"mom": new_m}

    return FunctionalOptimizer(init_fn, update_fn,
                               dict(lr=lr, momentum=mom, wd=wd))


def adam(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
         wd=0.0, clip_gradient=None):
    """Adam — semantics of adam_update (ref: optimizer_op.cc:146)."""
    lr = learning_rate

    def init_fn(params):
        return {"mean": _tree_map(jnp.zeros_like, params),
                "var": _tree_map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def update_fn(params, grads, state, scale):
        t = state["t"] + 1
        coef1 = 1.0 - beta1 ** t.astype(jnp.float32)
        coef2 = 1.0 - beta2 ** t.astype(jnp.float32)
        lr_t = lr * jnp.sqrt(coef2) / coef1

        def one(w, g, m, v):
            g = g * scale
            if clip_gradient is not None:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            g = g + wd * w
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * g * g
            w_new = w - lr_t * m_new / (jnp.sqrt(v_new) + epsilon)
            return w_new, m_new, v_new

        trip = _tree_map(one, params, grads, state["mean"], state["var"])
        is_t = lambda x: isinstance(x, tuple)  # noqa: E731
        return (_tree_map(lambda p: p[0], trip, is_leaf=is_t),
                {"mean": _tree_map(lambda p: p[1], trip, is_leaf=is_t),
                 "var": _tree_map(lambda p: p[2], trip, is_leaf=is_t),
                 "t": t})

    return FunctionalOptimizer(init_fn, update_fn,
                               dict(lr=lr, beta1=beta1, beta2=beta2))


def _nag(**kwargs):
    return sgd(nesterov=True, **kwargs)


_REGISTRY = {"sgd": sgd, "adam": adam, "nag": _nag}


def create(name, **kwargs):
    if callable(name):
        return name(**kwargs)
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(
            f"no functional optimizer '{name}'; available: "
            f"{sorted(_REGISTRY)} (use the imperative optimizer zoo "
            "for the others)")
    return _REGISTRY[key](**kwargs)
