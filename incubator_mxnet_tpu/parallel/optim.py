"""Functional (pytree) optimizers for compiled sharded training steps.

The imperative optimizer zoo (optimizer.py) applies updates key-by-key
through the Updater, mirroring the reference's fused optimizer ops
(ref: src/operator/optimizer_op.cc sgd_update:39, sgd_mom_update:66,
adam_update:146, mp_sgd_update:111).  Inside a pjit-compiled train
step the idiomatic form is a pure ``(params, grads, state) ->
(params, state)`` transform over pytrees, so the whole update fuses
into the step executable and inherits the parameter sharding — the
XLA analog of `update_on_kvstore` running the optimizer where the
reduced gradient lives (ref: src/kvstore/kvstore_dist_server.h
ApplyUpdates:176).

Multi-precision (`mp_`) behavior: pass ``master_dtype=jnp.float32``
and keep bf16 compute params alongside fp32 master weights.
"""
import jax
import jax.numpy as jnp

__all__ = ["FunctionalOptimizer", "sgd", "adam", "create",
           "warmup_cosine", "warmup_linear", "state_structure"]


def state_structure(state):
    """JSON-able description of an optimizer-state pytree, recorded
    in the sharded-checkpoint manifest (``extra['optimizer']``,
    docs/elastic.md) for operators and tooling: a human reading a
    manifest sees at a glance which optimizer family and layout the
    generation holds.  Load-path validation does NOT flow through
    this record — ``load_sharded`` enforces structure via its own
    key-set and shape/dtype checks."""
    import jax as _jax
    leaves = _jax.tree_util.tree_flatten_with_path(state)[0]
    return {_jax.tree_util.keystr(path):
            [list(map(int, leaf.shape)), str(leaf.dtype)]
            for path, leaf in leaves}


def _tree_map(f, *trees, **kw):
    return jax.tree_util.tree_map(f, *trees, **kw)


def _ones_tree(params):
    return _tree_map(lambda _: 1.0, params)


def _cast(v, dtype):
    """Cast a (possibly traced, strong-f32) scalar to the parameter's
    dtype so fp16/bf16 parameters are not silently upcast by the
    update arithmetic."""
    return jnp.asarray(v).astype(dtype)


def default_wd_mults(names, overrides=None):
    """The reference's wd_mult default rule (ref:
    python/mxnet/optimizer.py set_wd_mult/_get_wd): parameters whose
    name does not end in ``_weight``/``_gamma`` default to 0."""
    overrides = overrides or {}
    return {n: overrides.get(
        n, 1.0 if (n.endswith("_weight") or n.endswith("_gamma"))
        else 0.0) for n in names}


def scheduled_lr(opt):
    """Advance ``opt.num_update`` and return the lr for this update —
    the same increment-then-read order as the eager Updater path
    (ref: python/mxnet/optimizer.py _update_count then _get_lr)."""
    opt.num_update += 1
    if opt.lr_scheduler is not None:
        return opt.lr_scheduler(opt.num_update)
    return opt.lr


def _warmup_then(peak_lr, warmup_steps, total_steps, decay_fn):
    """Shared schedule shape: linear warmup to ``peak_lr`` over
    ``warmup_steps`` updates, then ``decay_fn(frac)`` where frac runs
    0->1 over the remaining steps.  Uses (t+1) so the FIRST update
    already has a non-zero lr — the same increment-then-read
    convention as the eager path (lr_scheduler.WarmupScheduler,
    optim.scheduled_lr).  jnp-traceable in the step count, so the
    whole schedule lives inside the compiled step (no per-step
    recompiles)."""
    def lr(t):
        u = jnp.asarray(t, jnp.float32) + 1.0
        warm = peak_lr * u / jnp.maximum(1.0, warmup_steps)
        frac = jnp.clip((u - warmup_steps)
                        / jnp.maximum(1.0, total_steps - warmup_steps),
                        0.0, 1.0)
        return jnp.where(u < warmup_steps, warm, decay_fn(frac))
    return lr


def warmup_cosine(peak_lr, warmup_steps, total_steps, end_lr=0.0):
    """Linear warmup then cosine decay to ``end_lr``."""
    return _warmup_then(
        peak_lr, warmup_steps, total_steps,
        lambda f: end_lr + 0.5 * (peak_lr - end_lr)
        * (1.0 + jnp.cos(jnp.pi * f)))


def warmup_linear(peak_lr, warmup_steps, total_steps, end_lr=0.0):
    """Linear warmup then linear decay to ``end_lr``."""
    return _warmup_then(
        peak_lr, warmup_steps, total_steps,
        lambda f: peak_lr + (end_lr - peak_lr) * f)


class FunctionalOptimizer:
    """A pure optimizer: init(params)->state; update(...)->new pair."""

    def __init__(self, init_fn, update_fn, hyper):
        self._init = init_fn
        self._update = update_fn
        self.hyper = hyper

    def init(self, params):
        return self._init(params)

    def update(self, params, grads, state, scale=1.0, lr=None,
               lr_mults=None, wd_mults=None):
        """``lr`` (scalar, may be traced) overrides the constructed
        learning rate — pass it as a jnp scalar argument so schedulers
        don't force recompiles.  ``lr_mults`` / ``wd_mults`` are
        per-leaf multiplier pytrees implementing the reference's
        lr_mult/wd_mult semantics (ref: python/mxnet/optimizer.py
        _get_lr/_get_wd — e.g. wd_mult defaults to 0 for non-weight,
        non-gamma parameters)."""
        return self._update(params, grads, state, scale, lr,
                            lr_mults, wd_mults)


def sgd(learning_rate=0.01, momentum=0.0, wd=0.0, clip_gradient=None,
        nesterov=False):
    """SGD(+momentum, +wd) — semantics of the reference's sgd_update /
    sgd_mom_update kernels (ref: src/operator/optimizer_op.cc:39,66):
    grad = scale*grad [clipped] + wd*weight; mom = m*mom - lr*grad;
    weight += mom.  With ``nesterov=True``, NAG semantics (ref:
    python/mxnet/optimizer.py NAG:592): mom = m*mom + grad;
    weight -= lr*(grad + m*mom)."""
    lr, mom, wdec = learning_rate, momentum, wd

    def init_fn(params):
        if mom == 0.0:
            return {}
        return {"mom": _tree_map(jnp.zeros_like, params)}

    def update_fn(params, grads, state, scale, lr_dyn=None,
                  lr_mults=None, wd_mults=None):
        base_lr = lr if lr_dyn is None else lr_dyn
        lr_mults = lr_mults or _ones_tree(params)
        wd_mults = wd_mults or _ones_tree(params)

        def one(w, g, m, lm, wm):
            g = g * _cast(scale, g.dtype)
            if clip_gradient is not None:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            g = g + (wdec * wm) * w
            lr_e = _cast(base_lr, w.dtype) * lm
            if m is None:
                return w - lr_e * g, None
            if nesterov:
                m_new = mom * m + g
                return w - lr_e * (g + mom * m_new), m_new
            m_new = mom * m - lr_e * g
            return w + m_new, m_new

        if mom == 0.0:
            new_p = _tree_map(
                lambda w, g, lm, wm: one(w, g, None, lm, wm)[0],
                params, grads, lr_mults, wd_mults)
            return new_p, state
        pairs = _tree_map(one, params, grads, state["mom"],
                          lr_mults, wd_mults)
        new_p = _tree_map(lambda pr: pr[0], pairs,
                          is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tree_map(lambda pr: pr[1], pairs,
                          is_leaf=lambda x: isinstance(x, tuple))
        return new_p, {"mom": new_m}

    return FunctionalOptimizer(init_fn, update_fn,
                               dict(lr=lr, momentum=mom, wd=wd))


def adam(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
         wd=0.0, clip_gradient=None):
    """Adam — semantics of adam_update (ref: optimizer_op.cc:146)."""
    lr = learning_rate

    def init_fn(params):
        return {"mean": _tree_map(jnp.zeros_like, params),
                "var": _tree_map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def update_fn(params, grads, state, scale, lr_dyn=None,
                  lr_mults=None, wd_mults=None):
        t = state["t"] + 1
        coef1 = 1.0 - beta1 ** t.astype(jnp.float32)
        coef2 = 1.0 - beta2 ** t.astype(jnp.float32)
        base_lr = lr if lr_dyn is None else lr_dyn
        lr_t = base_lr * jnp.sqrt(coef2) / coef1
        lr_mults = lr_mults or _ones_tree(params)
        wd_mults = wd_mults or _ones_tree(params)

        def one(w, g, m, v, lm, wm):
            g = g * _cast(scale, g.dtype)
            if clip_gradient is not None:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            g = g + (wd * wm) * w
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * g * g
            w_new = w - (_cast(lr_t, w.dtype) * lm) * m_new / (
                jnp.sqrt(v_new) + epsilon)
            return w_new, m_new, v_new

        trip = _tree_map(one, params, grads, state["mean"], state["var"],
                         lr_mults, wd_mults)
        is_t = lambda x: isinstance(x, tuple)  # noqa: E731
        return (_tree_map(lambda p: p[0], trip, is_leaf=is_t),
                {"mean": _tree_map(lambda p: p[1], trip, is_leaf=is_t),
                 "var": _tree_map(lambda p: p[2], trip, is_leaf=is_t),
                 "t": t})

    return FunctionalOptimizer(init_fn, update_fn,
                               dict(lr=lr, beta1=beta1, beta2=beta2))


def _nag(**kwargs):
    return sgd(nesterov=True, **kwargs)


_REGISTRY = {"sgd": sgd, "adam": adam, "nag": _nag}


def create(name, **kwargs):
    if callable(name):
        return name(**kwargs)
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(
            f"no functional optimizer '{name}'; available: "
            f"{sorted(_REGISTRY)} (use the imperative optimizer zoo "
            "for the others)")
    return _REGISTRY[key](**kwargs)


def from_imperative(opt):
    """Map an imperative ``optimizer.Optimizer`` onto its functional
    in-jit equivalent (None if it has no fused counterpart — callers
    fall back to the eager per-param updater)."""
    from .. import optimizer as opt_mod
    common = dict(learning_rate=opt.lr, wd=opt.wd,
                  clip_gradient=opt.clip_gradient)
    if getattr(opt, "multi_precision", False):
        # fp32-master-weight semantics live in the imperative mp_sgd
        # path (and in ShardedTrainStep's compute_dtype); no silent
        # downgrade here
        return None
    if isinstance(opt, opt_mod.NAG):
        return create("nag", momentum=opt.momentum, **common)
    if type(opt) is opt_mod.SGD:
        return create("sgd", momentum=opt.momentum, **common)
    if type(opt) is opt_mod.Adam:
        return create("adam", beta1=opt.beta1, beta2=opt.beta2,
                      epsilon=opt.epsilon, **common)
    return None
