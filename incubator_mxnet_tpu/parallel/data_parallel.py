"""Sharded compiled training step: the kvstore='tpu' execution path.

Replaces the reference's data-parallel machinery — batch slicing in
DataParallelExecutorGroup (ref: python/mxnet/module/executor_group.py:99)
plus gradient reduction through KVStore Comm trees / ps-lite push-pull
(ref: src/kvstore/comm.h:91,471; src/kvstore/kvstore_dist.h) — with a
single pjit-compiled step over a named mesh:

- the global batch is laid out sharded over the 'dp' (and optionally
  'sp') mesh axes; parameters are laid out per ShardingRules (
  replicated for pure DP, 'tp'-sharded for tensor parallelism);
- `jax.grad` of the mean loss over the global batch makes XLA emit
  the gradient all-reduce (psum over 'dp') on ICI automatically — this
  *is* the kvstore push/pull, fused into the step;
- the functional optimizer update runs where the parameters live
  (the analog of update_on_kvstore, ref:
  src/kvstore/kvstore_dist_server.h ApplyUpdates:176).

The sync-point discipline matches the reference: the step is async
(dispatch returns immediately); reading the loss (`float(...)`) is the
WaitForVar analog.
"""
import logging

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .functional import PureBlock, functionalize
from .mesh import (current_mesh, make_mesh, shard_batch,
                   use_mesh)
from . import optim as foptim
from .sharding import ShardingRules

__all__ = ["ShardedTrainStep"]


def _default_loss(outputs, labels):
    """Softmax cross-entropy on logits (config-1/2 default)."""
    logits = outputs[0].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1],
                            dtype=logits.dtype)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def _cast_floats(tree, dtype):
    """Cast float leaves of a pytree to ``dtype`` (ints untouched)."""
    def cast(v):
        if jnp.issubdtype(v.dtype, jnp.floating):
            return v.astype(dtype)
        return v
    return jax.tree_util.tree_map(cast, tree)


class ShardedTrainStep:
    """One compiled (fwd+bwd+optimizer) step over a device mesh.

    Parameters
    ----------
    block : gluon.HybridBlock (or a PureBlock)
    optimizer : str or FunctionalOptimizer ('sgd'/'adam')
    mesh : jax.sharding.Mesh (default: all devices on 'dp')
    loss_fn : callable(outputs:list[jax.Array], labels) -> scalar
    rules : ShardingRules for parameters (default: replicate)
    batch_axis / seq_axis : which input dims shard over 'dp' / 'sp'
    donate : donate param/state buffers (in-place update, the XLA
        analog of the reference's in-place optimizer kernels)
    compute_dtype : if set (e.g. jnp.bfloat16), the forward+backward
        runs in this dtype while fp32 master params receive the
        update — the reference's multi_precision / mp_sgd path (ref:
        src/operator/optimizer_op.cc MP_SGD), laid out TPU-style so
        the MXU sees bf16 operands.
    grad_accum : >1 splits the global batch into that many
        micro-batches inside ONE compiled step (lax.scan over grads),
        for effective batch sizes past the per-step memory budget.
        Global batch must be divisible by grad_accum (and the
        micro-batch by the 'dp' size).
    remat : rematerialize the forward during backward
        (jax.checkpoint) — activations recomputed, not stored.
    lr_schedule : callable(step:int32 tracer) -> lr, evaluated INSIDE
        the compiled step (optim.warmup_cosine / warmup_linear, or
        any jnp-traceable function) — no per-step recompiles.
    """

    def __init__(self, block, optimizer="sgd", optimizer_params=None,
                 mesh=None, loss_fn=None, rules=None, batch_axis=0,
                 seq_axis=None, donate=True, example_args=None,
                 compute_dtype=None, grad_accum=1, remat=False,
                 lr_schedule=None, zero=False):
        if mesh is None:
            mesh = current_mesh()  # ambient mesh from use_mesh(...)
        self.mesh = mesh if mesh is not None else make_mesh()
        if isinstance(block, PureBlock):
            self.pure = block
        else:
            self.pure = functionalize(block,
                                      *(example_args or ()))
        self.loss_fn = loss_fn or _default_loss
        if isinstance(optimizer, str):
            self.opt = foptim.create(optimizer,
                                     **(optimizer_params or {}))
        else:
            self.opt = optimizer
        if rules is None:
            # model-parallel meshes get the default Megatron/expert
            # rules out of the box: sharding is a LAYOUT choice, never
            # a semantics change (XLA derives the collectives), so the
            # only wrong default on a tp/ep mesh is full replication —
            # it silently wastes the axes the user asked for
            from .sharding import tp_rules_for_dense_stacks
            if (self.mesh.shape.get("tp", 1) > 1
                    or self.mesh.shape.get("ep", 1) > 1):
                # hand-built meshes may define only some axes: rules
                # touching absent axes drop to replicated
                rules = tp_rules_for_dense_stacks().restrict_to_axes(
                    self.mesh.axis_names)
        self.rules = rules or ShardingRules()
        self.batch_axis = batch_axis
        self.seq_axis = seq_axis
        self._donate = donate
        self.compute_dtype = compute_dtype
        self.grad_accum = max(1, int(grad_accum))
        self.remat = bool(remat)
        self.lr_schedule = lr_schedule
        self.step_count = jnp.zeros((), jnp.int32)

        # -- lay out current values over the mesh --------------------
        pvals = self.pure.params()
        svals = self.pure.states()
        self.param_shardings = self.rules.shardings(self.mesh, pvals)
        # what the forward/backward math wants (pre-ZeRO layout)
        self._compute_shardings = dict(self.param_shardings)
        self.zero = bool(zero) and self.mesh.shape.get("dp", 1) > 1
        if self.zero:
            # ZeRO-1: fp32 master params — and, via zeros_like
            # inheritance, every optimizer-state moment — live
            # dp-sharded; each dp rank updates only its slice and
            # GSPMD inserts the reduce-scatter/all-gather pair.
            # Memory per chip: params + opt state shrink by dp.
            # Rule-sharded (tp) leaves keep their layout.
            dp = self.mesh.shape["dp"]

            def zshard(name, a):
                base = self.param_shardings[name]
                if base.spec != P():
                    return base
                for ax, d in enumerate(a.shape):
                    if d > 0 and d % dp == 0:
                        spec = [None] * a.ndim
                        spec[ax] = "dp"
                        return NamedSharding(self.mesh, P(*spec))
                return base

            self.param_shardings = {n: zshard(n, a)
                                    for n, a in pvals.items()}
        self.state_shardings = {
            n: NamedSharding(self.mesh, P()) for n in svals}
        self.params = _owned_put_tree(pvals, self.param_shardings)
        self.states = _owned_put_tree(svals, self.state_shardings)
        self.opt_state = self.opt.init(self.params)
        self._step = None
        self._eval = None
        # perf observatory: armed by cost_analysis()/arm_perf(); a
        # ticking clock publishes train_mfu/train_mbu from wall time
        self._perf_clock = None
        # memory planner (docs/memory.md): the preflight gate's
        # accepted plan + the cached forward-liveness walk (both
        # bind-time artifacts — nothing here runs on the step path)
        self._mem_plan = None
        self._mem_liveness = None

    # ---------------------------------------------------------------- build
    def _input_sharding(self, ndim, is_label=False):
        seq = self.seq_axis
        if is_label or (seq is not None and ndim <= seq):
            seq = None
        return shard_batch(self.mesh, ndim, self.batch_axis, seq)

    def _build(self, x, y):
        pure, loss_fn, opt = self.pure, self.loss_fn, self.opt
        cdt = self.compute_dtype
        accum = int(self.grad_accum)
        apply = pure.apply
        if self.remat:
            # rematerialize the forward during backward: activations
            # are recomputed instead of stored, trading MXU FLOPs for
            # HBM — the jax.checkpoint lever the TPU memory budget
            # usually wants for long sequences / deep nets
            apply = jax.checkpoint(
                lambda p, s, xs, rng: pure.apply(
                    p, s, xs, rng, training=True))

        zero = self.zero
        compute_sh = self._compute_shardings

        def grad_of(params, states, xb, yb, rng):
            def lossf(p):
                xin = xb
                if cdt is not None:
                    p = _cast_floats(p, cdt)
                    xin = _cast_floats(xb, cdt)
                if zero:
                    # gather the dp-sharded masters back to the
                    # compute layout AFTER the low-precision cast, so
                    # the all-gather moves bf16 bytes, not fp32
                    p = jax.lax.with_sharding_constraint(
                        p, {n: compute_sh[n] for n in p})
                outs, new_states = apply(p, states, [xin], rng)
                return loss_fn(outs, yb), new_states
            return jax.value_and_grad(lossf, has_aux=True)(params)

        if accum > 1:
            if self.batch_axis != 0:
                raise ValueError(
                    "grad_accum > 1 requires batch_axis=0 (the "
                    "micro-batch split slices axis 0); move the "
                    "batch to axis 0 or accumulate manually")
            if x.shape[0] % accum != 0:
                raise ValueError(
                    f"global batch {x.shape[0]} is not divisible by "
                    f"grad_accum={accum}")

        sched = self.lr_schedule

        def step(params, states, opt_state, t, x, y, rng):
            if accum <= 1:
                (loss, new_states), grads = grad_of(
                    params, states, x, y, rng)
            else:
                # micro-batch scan: grads accumulate, aux states
                # (BN moving stats) thread through sequentially —
                # one compiled step regardless of accum factor
                xm = x.reshape((accum, x.shape[0] // accum)
                               + x.shape[1:])
                ym = y.reshape((accum, y.shape[0] // accum)
                               + y.shape[1:])
                rngs = jax.random.split(rng, accum)

                def micro(carry, xyr):
                    gsum, lsum, st = carry
                    xb, yb, r = xyr
                    (loss, new_st), g = grad_of(params, st, xb, yb, r)
                    gsum = jax.tree_util.tree_map(
                        lambda a, b: a + b, gsum, g)
                    return (gsum, lsum + loss, new_st), None

                zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
                (gsum, lsum, new_states), _ = jax.lax.scan(
                    micro, (zeros, jnp.zeros((), jnp.float32),
                            states), (xm, ym, rngs))
                grads = jax.tree_util.tree_map(
                    lambda g: g / accum, gsum)
                loss = lsum / accum
            lr = sched(t) if sched is not None else None
            new_params, new_opt = opt.update(params, grads, opt_state,
                                             lr=lr)
            return new_params, new_states, new_opt, t + 1, loss

        in_sh = (self.param_shardings, self.state_shardings,
                 None,  # opt state: inherit param sharding via init
                 None,  # step count
                 self._input_sharding(x.ndim),
                 self._input_sharding(y.ndim, is_label=True),
                 None)
        out_sh = (self.param_shardings, self.state_shardings,
                  None, None, NamedSharding(self.mesh, P()))
        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(step, in_shardings=in_sh, out_shardings=out_sh,
                       donate_argnums=donate)

    # ------------------------------------------------------- memory plan
    def _trace_liveness(self, x, y):
        """Abstract-shape walk of the forward loss (jaxpr_liveness) —
        the activation term of the memory plan.  Cached; traces once
        at preflight time, never on the step path."""
        if self._mem_liveness is not None:
            return
        from ..perf.memory_planner import jaxpr_liveness
        pure, loss_fn, cdt = self.pure, self.loss_fn, self.compute_dtype

        def fwd(p, s, xa, ya, rng):
            if cdt is not None:
                p = _cast_floats(p, cdt)
                xa = _cast_floats(xa, cdt)
            outs, _ = pure.apply(p, s, [xa], rng, training=True)
            return loss_fn(outs, ya)

        abst = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
        with use_mesh(self.mesh):
            self._mem_liveness = jaxpr_liveness(
                fwd, jax.tree_util.tree_map(abst, self.params),
                jax.tree_util.tree_map(abst, self.states),
                abst(x), abst(y),
                jax.ShapeDtypeStruct((2,), jnp.uint32))

    def _memory_plan(self, remat, grad_accum):
        """Per-device MemoryPlan for this step at the given knobs:
        sharded param/optimizer slice bytes (ZeRO/tp aware) + the
        traced activation liveness."""
        from ..perf import memory_planner as mp
        params_b = mp.sharded_tree_bytes(
            self.params, self.param_shardings) \
            + mp.tree_bytes(self.states)
        return mp.plan_memory(
            liveness=self._mem_liveness,
            params_bytes=params_b,
            max_param_bytes=mp.max_leaf_bytes(
                self.params, self.param_shardings),
            optimizer_bytes=mp.sharded_tree_bytes(self.opt_state),
            grad_accum=grad_accum, remat=remat,
            donate=self._donate,
            batch_shards=int(self.mesh.shape.get("dp", 1)))

    def _preflight(self, x, y):
        """Consult the analytic HBM plan before the first compile;
        under MXTPU_MEM_POLICY=degrade a predicted overflow walks the
        ladder (remat -> next grad_accum divisor) and the step adopts
        the surviving knobs.  Planner failures on exotic blocks are
        non-fatal (the gate is a guard, not a dependency); a dry
        ladder's MemoryPlanError stays loud."""
        from ..perf.memory_planner import preflight
        from ..resilience import MemoryPlanError
        try:
            self._trace_liveness(x, y)
            res = preflight(
                lambda r, a: self._memory_plan(r, a),
                site="sharded_train_step",
                device=self.mesh.devices.flat[0],
                can_remat=True,
                batch_size=int(x.shape[0])
                if self.batch_axis == 0 else 0,
                remat=self.remat, grad_accum=self.grad_accum)
        except MemoryPlanError:
            raise
        except Exception:
            logging.getLogger("mxtpu.memory").debug(
                "memory preflight skipped (planning failed)",
                exc_info=True)
            return
        if res is not None:
            self.remat = res.remat
            self.grad_accum = res.grad_accum
            self._mem_plan = res.plan

    def _oom_rung(self, oom, x):
        """One runtime degrade rung after a real (or injected) OOM at
        compile/execute: enable remat, else bump grad_accum to the
        next batch divisor, then rebuild for the single retry.  A dry
        ladder re-raises the typed OomError.  MXTPU_MEM_POLICY=off
        opts out of automatic degrading entirely — the OomError
        stays loud."""
        from .. import telemetry, tracing
        from ..perf.memory_planner import next_divisor
        from ..utils.env import get_env
        if str(get_env("MXTPU_MEM_POLICY")).lower() == "off":
            raise oom
        rung = None
        if not self.remat:
            self.remat, rung = True, "remat"
        elif self.batch_axis == 0:
            nxt = next_divisor(int(x.shape[0]), self.grad_accum)
            if nxt is not None:
                self.grad_accum, rung = nxt, f"grad_accum={nxt}"
        if rung is None:
            raise oom
        self._step = None   # rebuild with the new knobs
        telemetry.counter("oom_retries_total").inc()
        tracing.trace_event("mem_degrade", site="sharded_train_step",
                            rung=rung, cause="runtime_oom")
        logging.getLogger("mxtpu.memory").warning(
            "OOM at sharded_train_step: degrade ladder rung '%s', "
            "retrying once%s", rung,
            " (numerics change: smaller micro-batches)"
            if rung.startswith("grad_accum") else
            " (numerics unchanged; more compute)")

    # ---------------------------------------------------------------- run
    def __call__(self, x, y, rng=None):
        """Run one training step on a *global* batch; returns loss."""
        from ..dist import elastic_probe
        elastic_probe()     # elastic:rank<N> injection (docs/elastic.md)
        x, y = _raw(x), _raw(y)
        if rng is None:
            from .. import random_state
            rng = random_state.next_key()
        from ..resilience import as_oom_error, check_oom
        for attempt in (0, 1):
            try:
                if self._step is None:
                    self._preflight(x, y)
                    self._step = self._build(x, y)
                # mem:oom injection point (docs/resilience.md); a
                # no-op single bool check without MXTPU_FAULT_SPEC
                check_oom("sharded_train_step")
                xs = jax.device_put(x, self._input_sharding(x.ndim))
                ys = jax.device_put(
                    y, self._input_sharding(y.ndim, True))
                # run (and, on the first call, trace) with this
                # step's mesh ambient, so mesh-aware blocks (e.g.
                # ring attention) resolve the step's mesh even when
                # called outside use_mesh()
                with use_mesh(self.mesh):
                    (self.params, self.states, self.opt_state,
                     self.step_count, loss) = self._step(
                        self.params, self.states, self.opt_state,
                        self.step_count, xs, ys, rng)
                break
            except Exception as exc:
                oom = as_oom_error(exc, "sharded_train_step",
                                   plan=self._mem_plan)
                if oom is None:
                    raise
                if attempt:
                    raise oom from exc
                self._oom_rung(oom, x)   # raises when the ladder is dry
        if self._perf_clock is not None:
            self._perf_clock.tick()   # wall-clock only, no syncs
        return loss

    step = __call__

    def arm_perf(self, flops_per_step=0.0, bytes_per_step=0.0,
                 tokens_per_step=0.0, dtype=None):
        """Arm the train_mfu/train_mbu/train_tokens_per_sec gauges
        with an analytic per-step cost (e.g. from the graph cost
        model or ``model.train_flops_per_token * tokens``).  The
        clock reads only wall time — zero added device syncs."""
        from ..perf import TrainPerfClock
        if dtype is None:
            dtype = str(self.compute_dtype) if self.compute_dtype \
                else "float32"
        dev = self.mesh.devices.flat[0]
        if self._perf_clock is None:
            self._perf_clock = TrainPerfClock(
                flops_per_step, bytes_per_step, tokens_per_step,
                device=dev, dtype=dtype)
        else:
            self._perf_clock.arm(flops_per_step, bytes_per_step,
                                 tokens_per_step, device=dev)
        return self._perf_clock

    def memory_analysis(self, x, y):
        """XLA's compiled-buffer accounting for this train step (the
        reference's memonger/`mirror` cost question: how much HBM
        does one step hold?).  Returns the backend's MemoryAnalysis
        (``.temp_size_in_bytes`` = activations + scratch) or None
        when the backend doesn't report one.  Lowers from abstract
        shapes against the step's real shardings; no data moves and
        nothing executes (note: this AOT compile does not seed the
        jit cache — the first real step() still traces)."""
        x, y = _raw(x), _raw(y)
        if self._step is None:
            self._step = self._build(x, y)
        # avals only: lowering never touches values, so don't pay a
        # host->device copy of a global batch just to ask a question
        xa = jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=self._input_sharding(x.ndim))
        ya = jax.ShapeDtypeStruct(
            y.shape, y.dtype,
            sharding=self._input_sharding(y.ndim, True))
        rng = jax.random.PRNGKey(0)   # traced arg; value irrelevant
        with use_mesh(self.mesh):
            compiled = self._step.lower(
                self.params, self.states, self.opt_state,
                self.step_count, xa, ya, rng).compile()
        try:
            return compiled.memory_analysis()
        except Exception:   # oom-ok: probing an optional backend API
            return None

    def cost_analysis(self, x, y):
        """XLA's FLOP/bytes-accessed accounting for this train step —
        the sibling of :meth:`memory_analysis`, and the cross-check
        anchor for the analytic cost model (docs/observability.md
        "Perf observatory").  Returns ``{"flops", "bytes"}`` or None
        where the backend doesn't report.  On success the
        train_mfu/train_mbu clock is armed with the measured step
        cost (if not already armed), so subsequent steps publish MFU
        with no further compiles or syncs."""
        x, y = _raw(x), _raw(y)
        if self._step is None:
            self._step = self._build(x, y)
        xa = jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=self._input_sharding(x.ndim))
        ya = jax.ShapeDtypeStruct(
            y.shape, y.dtype,
            sharding=self._input_sharding(y.ndim, True))
        rng = jax.random.PRNGKey(0)   # traced arg; value irrelevant
        with use_mesh(self.mesh):
            compiled = self._step.lower(
                self.params, self.states, self.opt_state,
                self.step_count, xa, ya, rng).compile()
        from ..perf import xla_cost
        cost = xla_cost(compiled)
        if cost is not None and self._perf_clock is None:
            self.arm_perf(cost["flops"], cost["bytes"])
        return cost

    def evaluate(self, x, rng=None):
        """Compiled inference forward on a global batch."""
        x = _raw(x)
        if rng is None:
            from .. import random_state
            rng = random_state.next_key()
        if self._eval is None:
            pure = self.pure

            def ev(params, states, x, rng):
                outs, _ = pure.apply(params, states, [x], rng,
                                     training=False)
                return outs
            self._eval = jax.jit(ev)
        x = jax.device_put(x, self._input_sharding(x.ndim))
        with use_mesh(self.mesh):
            return self._eval(self.params, self.states, x, rng)

    def write_back(self):
        """Copy mesh values back into the Gluon Parameter objects.

        Hands the Parameters *owned copies*, never the step's own
        buffers — those are donated by the next step() and would turn
        the live Parameters into deleted arrays.
        """
        self.pure.write_back(_copy_tree(self.params),
                            _copy_tree(self.states))

    # ---------------------------------------------------------- checkpoint
    def save_checkpoint(self, path, data_state=None):
        """Write params + states + optimizer state to ``path`` (a
        checkpoint directory) in the native sharded-manifest format
        (parallel/checkpoint.py, docs/elastic.md): each rank writes
        only the slices it owns, a rank-0 manifest records the
        layout, and generations accumulate under the directory with
        corrupt-shard fallback on load.  ``data_state`` (an input
        iterator's ``state_dict()``) rides in the same generation so
        params and data cursors always travel together.  Values are
        copied first so the next step's buffer donation cannot race
        the write.  Returns the generation directory written."""
        from . import checkpoint as _ckpt
        return _ckpt.save_sharded(
            path, self._ckpt_tree(), self.mesh,
            step=int(self.step_count), data_state=data_state,
            extra={"optimizer": foptim.state_structure(
                self.opt_state)})

    def load_checkpoint(self, path):
        """Restore the newest valid generation under ``path`` INTO
        this step's mesh layout: every leaf is reassembled from the
        source slices that overlap this step's own shards, so resume
        works on a different mesh shape / world size than the save
        ran on (shrink and grow included).  Returns the loaded
        generation's data-iterator companion state (or None)."""
        from . import checkpoint as _ckpt
        tree = {"params": self.params, "states": self.states,
                "opt_state": self.opt_state,
                "step_count": self.step_count}
        restored, manifest, gen_dir = _ckpt.load_latest(
            path, tree, self.mesh)
        self.params = restored["params"]
        self.states = restored["states"]
        self.opt_state = restored["opt_state"]
        self.step_count = restored["step_count"]
        return _ckpt.load_data_companion(gen_dir, manifest)

    def _ckpt_tree(self):
        # generic pytree copy (opt_state nests beyond a flat dict)
        return _copy_tree({"params": self.params,
                           "states": self.states,
                           "opt_state": self.opt_state,
                           "step_count": self.step_count})


def _raw(a):
    from ..ndarray.ndarray import NDArray
    return a._data if isinstance(a, NDArray) else jnp.asarray(a)


def _owned_put_tree(vals, shardings):
    """Lay ``vals`` out per ``shardings`` in buffers this step *owns*.

    ``jax.device_put`` returns a view sharing the input's buffer when
    the value already lives on the target devices (and aliasing is
    undetectable on backends without unsafe_buffer_pointer, e.g.
    axon) — donating such a view in the compiled step would delete
    the caller's array (the live gluon Parameter, or a sibling
    ShardedTrainStep built on the same block).  Force a real copy via
    one compiled add over the whole tree (single compile, not one per
    parameter — compiles are expensive over remote backends).
    """
    placed = {n: jax.device_put(v, shardings[n])
              for n, v in vals.items()}
    if not placed:
        return placed
    return jax.jit(_copy_impl, out_shardings=shardings)(placed)


def _copy_impl(t):
    return jax.tree_util.tree_map(
        lambda a: a + jnp.zeros((), a.dtype), t)


# module-level fn so jax's jit cache is keyed on shapes/shardings and
# repeat constructions / write_backs hit the cache instead of
# re-tracing a fresh lambda every time
_copy_tree = jax.jit(_copy_impl)
