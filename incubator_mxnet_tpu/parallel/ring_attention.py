"""Ring attention: sequence/context parallelism over the 'sp' axis.

A new-capability design (the reference has nothing comparable — its
only long-sequence tool is bucketing, SURVEY.md §5): the sequence axis
is sharded over the 'sp' mesh axis; each device holds a Q block and
rotates K/V blocks around the ring with `lax.ppermute`, accumulating
attention with the numerically-stable blockwise (flash) recurrence
(running max m, normalizer l, weighted sum o).  Compute on the current
block overlaps with the ICI transfer of the next — the classic ring
schedule.  Differentiable: `jax.grad` through scan+ppermute yields the
reverse ring automatically.

Shapes (per device, inside shard_map over 'sp'):
    q, k, v : (batch, seq_local, heads, head_dim)
Causal masking uses global positions derived from axis_index('sp').
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention_local", "ring_attention",
           "shard_map_attention"]


def ring_attention_local(q, k, v, axis_name="sp", causal=False,
                         scale=None):
    """Ring attention body — call inside shard_map over `axis_name`."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    q = q * scale
    perm = [(i, (i + 1) % n) for i in range(n)]
    neg = jnp.asarray(-jnp.inf, q.dtype)  # -inf so the isfinite
    # guards below actually fire for fully-masked causal rows

    q_pos = idx * lq + jnp.arange(lq)  # global positions of our Q rows

    def body(carry, step):
        k_blk, v_blk, m, l, o = carry
        # which shard does this K/V block come from? it has been
        # ppermute'd `step` times, so it originated at idx - step
        src = (idx - step) % n
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk)
        if causal:
            k_pos = src * lk + jnp.arange(lk)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, neg)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # guard fully-masked rows (m_new == neg) against inf/nan
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - m_safe[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, neg))
        l_new = corr * l + jnp.sum(p, axis=-1)
        o_new = corr[..., None] * o + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, o_new), None

    m0 = jnp.full((b, h, lq), neg, q.dtype)
    l0 = jnp.zeros((b, h, lq), q.dtype)
    o0 = jnp.zeros((b, h, lq, d), q.dtype)
    (_, _, _, l, o), _ = jax.lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3))  # (B, Lq, H, D)


def shard_map_attention(local_fn, q, k, v, mesh, batch_axis="dp",
                        seq_axis="sp"):
    """Shared shard_map wrapper for sequence-parallel attention
    schemes (ring, ulysses): q/k/v are global (B, L, H, D) arrays
    laid out with B over `batch_axis` and L over `seq_axis`;
    ``local_fn(ql, kl, vl, axis_name)`` is the per-shard body."""
    if batch_axis is not None and \
            q.shape[0] % mesh.shape[batch_axis] != 0:
        batch_axis = None  # batch too small to split: replicate
    spec = P(batch_axis, seq_axis, None, None)

    if not isinstance(q, jax.core.Tracer):
        # eager call: concrete arrays may be committed to a single
        # device, which conflicts with shard_map's mesh — lay them
        # out over the mesh first (a no-op under jit tracing)
        sh = jax.sharding.NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(t, sh) for t in (q, k, v))

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def run(ql, kl, vl):
        return local_fn(ql, kl, vl, seq_axis)

    return run(q, k, v)


def ring_attention(q, k, v, mesh, causal=False, scale=None,
                   batch_axis="dp", seq_axis="sp"):
    """shard_map wrapper: q/k/v are global (B, L, H, D) arrays laid
    out with B over `batch_axis` and L over `seq_axis`."""
    def body(ql, kl, vl, axis_name):
        return ring_attention_local(ql, kl, vl, axis_name=axis_name,
                                    causal=causal, scale=scale)

    return shard_map_attention(body, q, k, v, mesh,
                               batch_axis=batch_axis,
                               seq_axis=seq_axis)
